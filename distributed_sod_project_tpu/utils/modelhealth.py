"""Training numerics telemetry (docs/OBSERVABILITY.md "Model health").

PR 9 made training *latency* observable (spans, sidecar /metrics);
this module makes training *numerics* observable: a run that is
silently diverging — exploding gradients, a parameter group gone NaN,
an update/weight ratio drifting out of the stable band — should be a
scraped gauge and a named alert, not a post-mortem.

Two halves, one seam:

- **In-program** (:func:`health_step_metrics`, called by all three
  step builders — DP ``train/step.py``, GSPMD ``parallel/tp.py``, SP
  ``parallel/sp.py`` — behind the ``health_numerics`` knob): per
  parameter-group gradient norms, the group index that FIRST went
  non-finite this step (provenance — ``optim.skip_nonfinite`` counts
  skips but cannot attribute them), and the update-to-weight ratio.
  All scalars, computed inside the compiled step (one extra pass over
  the grads/params trees); with the knob off the step program is
  byte-for-byte the historical one.
- **On-host** (:class:`HealthMonitor`): aggregates the per-step values
  the loop reads back at its normal metric cadence into the
  ``dsod_health_*`` Prometheus families the PR-9 trainer sidecar
  serves, and derives the scalar signals the alert engine
  (utils/alerts.py) watches.

Parameter groups are the TOP-LEVEL modules of the params tree (sorted
— e.g. ``backbone``, ``decoder``, ``head``): coarse enough to stay
cheap, fine enough that "which part of the model diverged first" has
an answer.  The grouping is a pure function of the tree structure, so
the in-program index and the host-side name list agree by
construction.

Observation cadence honesty: the loop feeds the monitor whenever it
fetches metrics — every chunk under ``steps_per_dispatch>1``, the
logging cadence at k=1 (fetching per step would add the host↔device
sync the chunked loop exists to avoid).  The carried
``notfinite_count`` (optax ``apply_if_finite``) still counts every
skip regardless; only the *attribution* is sampled at the fetch
cadence.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from .alerts import Rule

# Host metric keys that are NOT loss components (everything else in
# the device metric dict at fetch time is one).
_NON_LOSS_KEYS = frozenset(("grad_norm", "lr", "notfinite_count",
                            "epoch", "imgs_per_sec"))

PREFIX = "health/"
NONFINITE_KEY = PREFIX + "nonfinite_group"
UPDATE_RATIO_KEY = PREFIX + "update_weight_ratio"
WEIGHT_NORM_KEY = PREFIX + "weight_norm"
GROUP_PREFIX = PREFIX + "grad_group_norm/"


def param_group_names(params) -> Tuple[str, ...]:
    """Sorted top-level module names of a params tree — the shared
    group order for the in-program provenance index and the host-side
    name mapping.  A non-mapping tree is one group, ``params``."""
    try:
        keys = sorted(str(k) for k in params.keys())
    except AttributeError:
        return ("params",)
    return tuple(keys) if keys else ("params",)


def _group_subtrees(tree) -> List[Tuple[str, object]]:
    try:
        keys = sorted(str(k) for k in tree.keys())
    except AttributeError:
        keys = []
    if not keys:
        return [("params", tree)]
    return [(k, tree[k]) for k in keys]


def health_step_metrics(params, grads, new_params) -> Dict[str, object]:
    """The in-program numerics scalars for one step (call with the
    POST-reduction grads so every replica logs identical values):

    - ``health/grad_group_norm/<group>`` — per-group gradient global
      norm (NaN when that group's grads are non-finite — the raw
      truth rides the metric stream; the host monitor sanitizes for
      Prometheus).
    - ``health/nonfinite_group`` — index (in sorted group order) of
      the FIRST group with a non-finite gradient this step, −1 when
      all finite.
    - ``health/update_weight_ratio`` — ‖params′ − params‖ / ‖params‖
      (0 when the update was skipped by ``apply_if_finite``).
    - ``health/weight_norm`` — ‖params‖.
    """
    import jax
    import jax.numpy as jnp
    import optax

    groups = _group_subtrees(grads)
    metrics: Dict[str, object] = {}
    flags = []
    for name, sub in groups:
        leaves = jax.tree_util.tree_leaves(sub)
        metrics[GROUP_PREFIX + name] = optax.global_norm(sub)
        if leaves:
            nf = jnp.any(jnp.stack(
                [jnp.any(~jnp.isfinite(leaf)) for leaf in leaves]))
        else:
            nf = jnp.asarray(False)
        flags.append(nf)
    flags = jnp.stack(flags)
    metrics[NONFINITE_KEY] = jnp.where(
        jnp.any(flags), jnp.argmax(flags), -1).astype(jnp.float32)
    upd = optax.global_norm(jax.tree_util.tree_map(
        lambda a, b: a - b, new_params, params))
    wn = optax.global_norm(params)
    metrics[WEIGHT_NORM_KEY] = wn
    metrics[UPDATE_RATIO_KEY] = upd / (wn + 1e-12)
    return metrics


def default_numerics_rules(for_s: float = 0.0, clear_s: float = 30.0
                           ) -> List[Rule]:
    """The built-in training alert set (custom rules ride
    ``health_alert_rules``):

    - ``numerics_nonfinite`` — any step in the observed interval
      produced a non-finite gradient (fires immediately, provenance
      group in the detail; ``hint="rollback"`` for the opt-in
      supervisor hand-off).
    - ``grad_norm_spike`` / ``loss_spike`` — EWMA z-score > 6 on the
      gradient norm / total loss (the slow-divergence shape a plain
      threshold cannot know the scale of in advance).
    """
    return [
        Rule("numerics_nonfinite", "nonfinite_interval", "gt", 0.0,
             for_s=0.0, clear_s=clear_s, hint="rollback"),
        Rule("grad_norm_spike", "grad_norm", "z", 6.0,
             for_s=for_s, clear_s=clear_s),
        Rule("loss_spike", "loss_total", "z", 6.0,
             for_s=for_s, clear_s=clear_s),
    ]


def _finite(v: Optional[float]) -> float:
    """NaN/None → 0.0 for Prometheus gauge rendering (the raw value
    still rides snapshot()/the metric stream)."""
    if v is None or v != v or v in (float("inf"), float("-inf")):
        return 0.0
    return float(v)


class HealthMonitor:
    """Host-side aggregation of the in-program numerics metrics.

    Thread-safe: the train loop writes at its metric cadence while the
    telemetry sidecar renders ``prom_families`` concurrently (the same
    concurrent-reader contract PipelineStats honors).
    """

    def __init__(self, group_names: Tuple[str, ...]):
        if not group_names:
            raise ValueError("HealthMonitor needs at least one group")
        self.group_names = tuple(group_names)
        self._lock = threading.Lock()
        self._steps = 0
        self._nonfinite_total = 0
        self._nonfinite_by_group = {g: 0 for g in self.group_names}
        self._recent_nonfinite = 0          # since the last signals() read
        self._last_nonfinite_group = ""
        self._grad_norm: Optional[float] = None
        self._update_ratio: Optional[float] = None
        self._weight_norm: Optional[float] = None
        self._group_norms: Dict[str, Optional[float]] = {
            g: None for g in self.group_names}
        self._loss: Dict[str, float] = {}
        self._notfinite_consec = 0.0

    # -- ingest --------------------------------------------------------

    def observe(self, metrics_host: Dict) -> None:
        """Feed one fetched device-metric dict.  Leaves may be
        (k,)-stacked under step chunking: counters sweep EVERY entry
        (a mid-chunk NaN must not hide behind a clean last step);
        gauges keep the last entry — exactly the value a k=1 loop
        would report at this boundary."""
        import numpy as np

        def flat(v):
            return np.asarray(v, dtype=np.float64).reshape(-1)

        nf = metrics_host.get(NONFINITE_KEY)
        with self._lock:
            if nf is not None:
                idxs = flat(nf)
                self._steps += len(idxs)
                for i in idxs:
                    if i >= 0:
                        g = self.group_names[min(int(i),
                                                 len(self.group_names) - 1)]
                        self._nonfinite_total += 1
                        self._nonfinite_by_group[g] += 1
                        self._recent_nonfinite += 1
                        self._last_nonfinite_group = g
            for key, v in metrics_host.items():
                if not key.startswith(GROUP_PREFIX):
                    continue
                g = key[len(GROUP_PREFIX):]
                if g in self._group_norms:
                    self._group_norms[g] = float(flat(v)[-1])
            for key, attr in ((UPDATE_RATIO_KEY, "_update_ratio"),
                              (WEIGHT_NORM_KEY, "_weight_norm"),
                              ("grad_norm", "_grad_norm")):
                v = metrics_host.get(key)
                if v is not None:
                    setattr(self, attr, float(flat(v)[-1]))
            v = metrics_host.get("notfinite_count")
            if v is not None:
                self._notfinite_consec = float(flat(v)[-1])
            for key, v in metrics_host.items():
                if (key.startswith(PREFIX) or key in _NON_LOSS_KEYS
                        or key.startswith("data_")):
                    continue
                arr = flat(v)
                if arr.size:
                    self._loss[key] = float(arr[-1])

    # -- alert signals -------------------------------------------------

    def signals(self) -> Tuple[Dict[str, float], Dict[str, str]]:
        """``(signals, details)`` for the alert engine.
        ``nonfinite_interval`` is the count of non-finite steps
        observed since the previous read (reset on read — the alert's
        clear dwell, not this counter, provides the hold)."""
        with self._lock:
            recent = self._recent_nonfinite
            self._recent_nonfinite = 0
            sigs = {
                "nonfinite_interval": float(recent),
                "notfinite_consecutive": self._notfinite_consec,
            }
            if self._grad_norm is not None:
                sigs["grad_norm"] = self._grad_norm
            if self._update_ratio is not None:
                sigs["update_weight_ratio"] = self._update_ratio
            if "total" in self._loss:
                sigs["loss_total"] = self._loss["total"]
            detail = (f"group={self._last_nonfinite_group}"
                      if self._last_nonfinite_group else "")
        details = {"nonfinite_interval": detail,
                   "notfinite_consecutive": detail} if detail else {}
        return sigs, details

    # -- surfaces ------------------------------------------------------

    def snapshot(self) -> Dict:
        with self._lock:
            return {
                "steps_observed": self._steps,
                "nonfinite_total": self._nonfinite_total,
                "nonfinite_by_group": dict(self._nonfinite_by_group),
                "last_nonfinite_group": self._last_nonfinite_group,
                "grad_norm": self._grad_norm,
                "update_weight_ratio": self._update_ratio,
                "weight_norm": self._weight_norm,
                "grad_group_norms": dict(self._group_norms),
                "loss": dict(self._loss),
                "notfinite_consecutive": self._notfinite_consec,
            }

    def prom_families(self, labels: str = ""):
        """The ``dsod_health_*`` families (trainer sidecar /metrics).
        Every family renders unconditionally — zero-valued while idle —
        so the inventory (tools/metrics_lint.py) is run-independent."""
        with self._lock:
            steps = self._steps
            nft = self._nonfinite_total
            by_group = dict(self._nonfinite_by_group)
            gnorms = dict(self._group_norms)
            gauges = [
                ("dsod_health_grad_norm", _finite(self._grad_norm)),
                ("dsod_health_update_weight_ratio",
                 _finite(self._update_ratio)),
                ("dsod_health_weight_norm", _finite(self._weight_norm)),
                ("dsod_health_notfinite_consecutive",
                 _finite(self._notfinite_consec)),
            ]
            loss = dict(self._loss)
        sb = f"{{{labels}}}" if labels else ""
        pre = f"{labels}," if labels else ""
        fams = [
            ("dsod_health_steps_observed_total", "counter",
             [f"dsod_health_steps_observed_total{sb} {steps}"]),
            ("dsod_health_nonfinite_total", "counter",
             [f"dsod_health_nonfinite_total{sb} {nft}"]),
            ("dsod_health_nonfinite_group_total", "counter",
             ['dsod_health_nonfinite_group_total{%sgroup="%s"} %d'
              % (pre, g, by_group[g]) for g in self.group_names]),
            ("dsod_health_grad_group_norm", "gauge",
             ['dsod_health_grad_group_norm{%sgroup="%s"} %g'
              % (pre, g, _finite(gnorms[g])) for g in self.group_names]),
        ]
        for name, v in gauges:
            fams.append((name, "gauge", [f"{name}{sb} {v:g}"]))
        fams.append(("dsod_health_loss", "gauge", [
            'dsod_health_loss{%scomponent="%s"} %g'
            % (pre, k, _finite(v)) for k, v in sorted(loss.items())]
            or ['dsod_health_loss{%scomponent="total"} 0' % pre]))
        return fams
