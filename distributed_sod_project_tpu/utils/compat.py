"""jax API-surface compatibility shims.

The codebase targets the current public API; this module papers over
the renames between the jax versions the images we run on actually
ship, so a version skew degrades to a shim instead of an
AttributeError twenty minutes into a TPU window.

- ``shard_map``: public ``jax.shard_map`` (jax ≥ 0.6) vs
  ``jax.experimental.shard_map.shard_map`` (0.4.x), including the
  ``check_vma`` → ``check_rep`` keyword rename.
- ``axis_size``: ``lax.axis_size`` (new) vs the ``psum(1, axis)``
  idiom (0.4.x) — the result is the static mesh-axis extent either way.
"""

from __future__ import annotations

import jax
from jax import lax

if hasattr(lax, "axis_size"):
    axis_size = lax.axis_size
else:
    def axis_size(axis_name):
        """Static extent of a named mesh axis inside shard_map."""
        return lax.psum(1, axis_name)

_native = getattr(jax, "shard_map", None)

if _native is not None:
    shard_map = _native
else:
    from jax.experimental.shard_map import shard_map as _exp_shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None, **kw):
        """0.4.x fallback: same signature as ``jax.shard_map``."""
        if check_vma is not None and "check_rep" not in kw:
            kw["check_rep"] = check_vma
        return _exp_shard_map(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, **kw)
