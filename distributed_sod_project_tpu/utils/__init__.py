from .logging import get_logger, is_primary_process
from .timing import StepTimer

__all__ = ["get_logger", "is_primary_process", "StepTimer"]
