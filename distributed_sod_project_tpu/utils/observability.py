"""Observability: metric writers, profiler traces, preemption handling.

SURVEY.md §5 rows "tracing/profiling", "metrics/logging" and "failure
detection": the reference had a rank-0 file/console logger + TensorBoard
and nothing for preemption beyond --resume restarts.  TPU-native forms:

- ``MetricWriter``: clu.metric_writers (TensorBoard event files) on the
  primary process, no-op elsewhere — scalars stream from the train loop.
- ``profile_window``: ``jax.profiler`` trace of a step range; the dump
  opens in TensorBoard/Perfetto and shows per-HLO timing on device.
- ``PreemptionGuard``: SIGTERM/SIGINT → finish the current step, write
  a final checkpoint, exit 0.  TPU pods are preemptible by design; a
  final-checkpoint-on-SIGTERM is the idiomatic elasticity story (the
  next run --resume's from it).
"""

from __future__ import annotations

import contextlib
import signal
import threading
from typing import Dict, Optional

from .logging import get_logger, is_primary_process


class PipelineStats:
    """Thread-safe counters/gauges for the host data plane.

    Every blocking point in the input pipeline (data/pipeline.py)
    reports here, so "the step is input-bound" is a measured number
    instead of a guess.  Counters (cumulative):

    - ``data_starved_ms``   — consumer blocked on an empty prefetch
      queue: device idle waiting for data.  THE input-bound signal.
    - ``data_h2d_ms``       — time inside device_put / global array
      assembly on the H2D thread.
    - ``data_prefetch_full_ms`` — H2D thread blocked on a full queue
      (healthy: the step, not the input, is the bottleneck).
    - ``data_build_wait_ms`` — loader blocked waiting for a batch
      build worker (decode+augment stage is the bottleneck).
    - ``data_ring_wait_ms`` — builders blocked waiting for a free
      batch buffer (consumer holding the ring; raise ring_buffers).
    - ``data_batches``      — batches produced.

    Queue depth is tracked as a running (sum, count) pair and reported
    as ``data_queue_depth_avg`` / ``data_queue_size``.

    ``delta()`` returns metrics accumulated since the previous
    ``delta()`` call — the train loop calls it once per logging
    interval and hands the result to :class:`MetricWriter`, so the
    TensorBoard curves are per-interval, not monotone totals.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._counts: Dict[str, float] = {}
        self._last: Dict[str, float] = {}
        self._depth_sum = 0.0
        self._depth_n = 0
        self._depth_size = 0

    def add(self, key: str, value: float) -> None:
        with self._lock:
            self._counts[key] = self._counts.get(key, 0.0) + float(value)

    def observe_depth(self, depth: int, size: int) -> None:
        with self._lock:
            self._depth_sum += depth
            self._depth_n += 1
            self._depth_size = size

    def snapshot(self) -> Dict[str, float]:
        """Cumulative totals (plus average queue depth over the run)."""
        with self._lock:
            out = dict(self._counts)
            if self._depth_n:
                out["data_queue_depth_avg"] = self._depth_sum / self._depth_n
                out["data_queue_size"] = float(self._depth_size)
            return out

    def delta(self) -> Dict[str, float]:
        """Counters accumulated since the last ``delta()`` call."""
        with self._lock:
            out = {}
            for k, v in self._counts.items():
                out[k] = v - self._last.get(k, 0.0)
            self._last = dict(self._counts)
            if self._depth_n:
                out["data_queue_depth_avg"] = self._depth_sum / self._depth_n
                self._depth_sum = 0.0
                self._depth_n = 0
            return out

    # The documented counter set (every blocking point above plus the
    # chunk-assembly stage) rendered UNCONDITIONALLY, so the /metrics
    # family inventory is stable across runs and platforms — a family
    # that happens to be zero this run must not read as "vanished" to
    # tools/metrics_lint.py.
    CANONICAL = ("data_starved_ms", "data_h2d_ms", "data_prefetch_full_ms",
                 "data_build_wait_ms", "data_ring_wait_ms", "data_batches",
                 "data_chunk_assemble_ms", "data_chunks",
                 "data_partial_chunks_dropped")

    def prom_families(self, labels: str = "", prefix: str = "dsod_train_"):
        """The host-data-plane telemetry as Prometheus families (the
        trainer sidecar's half of the rendering the serve stack already
        does through ``ServeStats.prom_families``)."""
        with self._lock:
            counts = dict(self._counts)
            depth = (self._depth_sum / self._depth_n
                     if self._depth_n else 0.0)
            size = self._depth_size
        sb = f"{{{labels}}}" if labels else ""
        fams = []
        for key in self.CANONICAL:
            name = f"{prefix}{key}_total"
            fams.append((name, "counter",
                         [f"{name}{sb} {counts.pop(key, 0.0):g}"]))
        for key in sorted(counts):  # anything non-canonical still shows
            name = f"{prefix}{key}_total"
            fams.append((name, "counter",
                         [f"{name}{sb} {counts[key]:g}"]))
        for name, v in ((f"{prefix}data_queue_depth_avg", depth),
                        (f"{prefix}data_queue_size", float(size))):
            fams.append((name, "gauge", [f"{name}{sb} {v:g}"]))
        return fams


def _merge_labels(*parts: str) -> str:
    """Merge pre-rendered label fragments (``'model="m"'``,
    ``'arm="bf16"'``) into one label set, skipping empties."""
    return ",".join(p for p in parts if p)


def render_prom_families(families) -> str:
    """Family list → Prometheus text: ``# TYPE`` once per family, then
    every sample line (the text-format rule promtool/OpenMetrics
    parsers enforce — a family's samples must be one contiguous group
    under a single TYPE line)."""
    lines = []
    for name, typ, samples in families:
        lines.append(f"# TYPE {name} {typ}")
        lines.extend(samples)
    return "\n".join(lines) + "\n"


def merge_prom_families(groups):
    """Concatenate several family lists (e.g. one per fleet replica,
    each already carrying its ``model=`` label) into one list with each
    family appearing ONCE — the aggregation a fleet /metrics endpoint
    must do so that per-replica series share metric families instead of
    re-declaring them.  Raises on a type conflict for the same family
    name."""
    order, merged = [], {}
    for fams in groups:
        for name, typ, samples in fams:
            if name not in merged:
                merged[name] = (typ, [])
                order.append(name)
            elif merged[name][0] != typ:
                raise ValueError(
                    f"metric family {name!r} declared as both "
                    f"{merged[name][0]!r} and {typ!r}")
            merged[name][1].extend(samples)
    return [(n,) + tuple(merged[n]) for n in order]


def _inject_labels(sample: str, labels: str) -> str:
    """Merge ``labels`` into one exposition sample line."""
    head, _, _ = sample.partition(" ")
    if "{" in head:
        return sample.replace("{", "{" + labels + ",", 1)
    name, _, rest = sample.partition(" ")
    return f"{name}{{{labels}}} {rest}"


def parse_prom_text(text: str, labels: str = ""):
    """Prometheus exposition text → family list
    ``[(name, type, [sample, ...]), ...]`` with ``labels`` injected
    into every sample — how a fleet router relabels a REMOTE replica's
    scraped /metrics under its ``model=`` key before merging.  Samples
    appearing before any ``# TYPE`` line get an ``untyped`` family per
    metric name."""
    fams = []
    cur = None
    untyped = {}
    for raw in text.splitlines():
        line = raw.strip()
        if not line:
            continue
        if line.startswith("# TYPE "):
            parts = line.split()
            if len(parts) < 4:
                continue
            cur = (parts[2], parts[3], [])
            fams.append(cur)
            continue
        if line.startswith("#"):
            continue
        if labels:
            line = _inject_labels(line, labels)
        if cur is not None:
            cur[2].append(line)
        else:
            name = line.partition("{")[0].partition(" ")[0]
            fam = untyped.get(name)
            if fam is None:
                fam = untyped[name] = (name, "untyped", [])
                fams.append(fam)
            fam[2].append(line)
    return fams


class LatencyHistogram:
    """Fixed-bucket latency histogram (milliseconds) with Prometheus
    rendering and bucket-interpolated percentiles.

    Prometheus-shaped on purpose: cumulative ``le`` buckets plus
    ``_sum``/``_count``, so ``render_prometheus`` is a straight dump and
    any scrape-side histogram_quantile() agrees with the in-process
    ``percentile()`` (both interpolate linearly inside a bucket).
    """

    BOUNDS_MS = (1.0, 2.0, 5.0, 10.0, 20.0, 50.0, 100.0, 200.0, 500.0,
                 1000.0, 2000.0, 5000.0, 10000.0)

    def __init__(self, bounds=BOUNDS_MS):
        self._bounds = tuple(float(b) for b in bounds)
        self._counts = [0] * (len(self._bounds) + 1)  # +1: overflow
        self._sum = 0.0
        self._n = 0
        self._lock = threading.Lock()

    def observe(self, ms: float) -> None:
        ms = float(ms)
        with self._lock:
            self._sum += ms
            self._n += 1
            for i, b in enumerate(self._bounds):
                if ms <= b:
                    self._counts[i] += 1
                    return
            self._counts[-1] += 1

    @property
    def count(self) -> int:
        with self._lock:
            return self._n

    @property
    def sum_ms(self) -> float:
        """Total observed ms — the ``_sum`` sample, exposed for
        stage-share attribution (utils/capacity.py divides the device
        histogram's sum by the e2e histogram's sum)."""
        with self._lock:
            return self._sum

    def percentile(self, p: float) -> float:
        """p in [0, 1] → estimated latency ms (linear interpolation
        inside the bucket; the overflow bucket reports its lower
        bound — an honest floor, not an invented tail)."""
        with self._lock:
            if not self._n:
                return 0.0
            target = p * self._n
            cum = 0
            lo = 0.0
            for i, b in enumerate(self._bounds):
                c = self._counts[i]
                if cum + c >= target and c:
                    frac = (target - cum) / c
                    return lo + (b - lo) * min(max(frac, 0.0), 1.0)
                cum += c
                lo = b
            return self._bounds[-1]

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            n, s = self._n, self._sum
        return {
            "count": float(n),
            "sum_ms": round(s, 3),
            "p50_ms": round(self.percentile(0.50), 3),
            "p95_ms": round(self.percentile(0.95), 3),
            "p99_ms": round(self.percentile(0.99), 3),
        }

    def prom_lines(self, name: str, labels: str = "",
                   include_type: bool = True) -> list:
        """Prometheus exposition lines; ``labels`` is a pre-rendered
        label set (e.g. ``arm="bf16"``) merged into every sample so
        per-arm histograms share one metric family (pass
        ``include_type=False`` for every family member after the first
        — TYPE may appear only once per family)."""
        with self._lock:
            counts = list(self._counts)
            s, n = self._sum, self._n
        pre = f"{labels}," if labels else ""
        suf = f"{{{labels}}}" if labels else ""
        lines = [f"# TYPE {name} histogram"] if include_type else []
        cum = 0
        for b, c in zip(self._bounds, counts):
            cum += c
            lines.append(f'{name}_bucket{{{pre}le="{b:g}"}} {cum}')
        lines.append(f'{name}_bucket{{{pre}le="+Inf"}} {n}')
        lines.append(f"{name}_sum{suf} {s:g}")
        lines.append(f"{name}_count{suf} {n}")
        return lines


class TailEstimator:
    """Windowed latency-tail estimate over the last ``window``
    observations (exact order statistic, not a histogram bound).

    The fleet router keeps one per model to pick the tail-latency
    HEDGE trigger (serve/failover.py ``pick_hedge_delay``): hedging at
    an EWMA would hedge half of all traffic, hedging at a fixed guess
    would miss regime changes — the observed p95 over a sliding window
    tracks the actual tail cheaply (the window is a few hundred floats
    and percentile() sorts only on demand, off the hot path)."""

    def __init__(self, window: int = 256):
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        self._window = int(window)
        self._buf = []
        self._i = 0
        self._lock = threading.Lock()

    def observe(self, ms: float) -> None:
        with self._lock:
            if len(self._buf) < self._window:
                self._buf.append(float(ms))
            else:  # ring overwrite: O(1), no deque rotation
                self._buf[self._i] = float(ms)
                self._i = (self._i + 1) % self._window

    @property
    def count(self) -> int:
        with self._lock:
            return len(self._buf)

    def percentile(self, p: float) -> Optional[float]:
        """p in [0, 1] → the windowed order statistic, or None before
        the first observation (callers must not invent a tail)."""
        with self._lock:
            if not self._buf:
                return None
            s = sorted(self._buf)
        i = min(int(p * len(s)), len(s) - 1)
        return s[i]


class ArmStats:
    """Per-precision-arm serving telemetry (one instance per arm,
    created lazily by :meth:`ServeStats.arm`): the latency tail and the
    padding tax are only actionable split per compiled-program family,
    because the arms are different programs with different device
    costs."""

    def __init__(self):
        self._lock = threading.Lock()
        self.device_ms = LatencyHistogram()
        self.e2e_ms = LatencyHistogram()
        self._served = 0
        self._occ_sum = 0
        self._occ_slots = 0

    def inc_served(self, n: int = 1) -> None:
        with self._lock:
            self._served += n

    def observe_batch(self, occupancy: int, bucket: int) -> None:
        with self._lock:
            self._occ_sum += int(occupancy)
            self._occ_slots += int(bucket)

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            out = {"served": float(self._served)}
            if self._occ_slots:
                out["batch_occupancy"] = round(
                    self._occ_sum / self._occ_slots, 4)
        for name, h in (("device", self.device_ms), ("e2e", self.e2e_ms)):
            for k, v in h.snapshot().items():
                out[f"{name}_{k}"] = v
        return out


class ServeStats:
    """Thread-safe serving telemetry (serve/ subsystem; docs/SERVING.md).

    Request accounting invariant — checked by tests/test_serving.py and
    worth checking on any live deployment's /metrics:

        served + shed + expired + errors == submitted   (eventually)

    every submitted request terminates in exactly one of the four.
    Latency histograms split the end-to-end path at its two seams:
    ``queue_ms`` (arrival → dispatch: coalescing wait + backlog),
    ``device_ms`` (dispatch → device fetch complete), ``e2e_ms``
    (arrival → response ready).  Batch occupancy records how full the
    static batch buckets run (occupancy_sum / occupancy_batches — the
    padding tax is 1 minus that ratio over the bucket sizes).  Each
    precision arm additionally owns an :class:`ArmStats` (device/e2e
    histograms, served count, occupancy) exposed under ``arm=`` labels
    in /metrics, so loadgen curves and dashboards split per arm.
    ``degraded`` is the ladder level (0 = full quality); the
    entered/exited counters tick on the 0 ↔ >0 boundary.
    """

    COUNTERS = ("submitted", "served", "shed", "expired", "errors",
                "batches", "reloads", "degraded_entered", "degraded_exited")

    def __init__(self):
        self._lock = threading.Lock()
        self._counts = {k: 0 for k in self.COUNTERS}
        self.queue_ms = LatencyHistogram()
        self.device_ms = LatencyHistogram()
        self.e2e_ms = LatencyHistogram()
        self._arms: Dict[str, ArmStats] = {}
        self._occ_sum = 0
        self._occ_slots = 0
        self._queue_depth = 0
        self._inflight = 0
        self._degraded_level = 0
        self._healthy = True
        self._health_reason = ""

    def inc(self, key: str, n: int = 1) -> None:
        with self._lock:
            self._counts[key] += n

    def arm(self, name: str) -> ArmStats:
        """The named arm's stats, created on first touch (lazy so the
        metric surface only shows arms that actually served)."""
        with self._lock:
            st = self._arms.get(name)
            if st is None:
                st = self._arms[name] = ArmStats()
            return st

    def observe_batch(self, occupancy: int, bucket: int,
                      arm: Optional[str] = None) -> None:
        with self._lock:
            self._counts["batches"] += 1
            self._occ_sum += int(occupancy)
            self._occ_slots += int(bucket)
        if arm is not None:
            self.arm(arm).observe_batch(occupancy, bucket)

    def set_queue_depth(self, depth: int) -> None:
        with self._lock:
            self._queue_depth = int(depth)

    def set_inflight(self, n: int) -> None:
        with self._lock:
            self._inflight = int(n)

    def set_degraded(self, level) -> None:
        """Feed the current ladder level (bool accepted for the binary
        callers: True == 1)."""
        level = int(level)
        with self._lock:
            if level > 0 and self._degraded_level == 0:
                self._counts["degraded_entered"] += 1
            elif level == 0 and self._degraded_level > 0:
                self._counts["degraded_exited"] += 1
            self._degraded_level = level

    def set_health(self, healthy: bool, reason: str = "") -> None:
        with self._lock:
            self._healthy = bool(healthy)
            self._health_reason = reason

    @property
    def healthy(self) -> bool:
        with self._lock:
            return self._healthy

    @property
    def health_reason(self) -> str:
        with self._lock:
            return self._health_reason

    @property
    def degraded(self) -> bool:
        with self._lock:
            return self._degraded_level > 0

    @property
    def degraded_level(self) -> int:
        with self._lock:
            return self._degraded_level

    def counter(self, key: str) -> int:
        with self._lock:
            return self._counts[key]

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            out = {k: float(v) for k, v in self._counts.items()}
            out["queue_depth"] = float(self._queue_depth)
            out["inflight"] = float(self._inflight)
            out["degraded"] = float(self._degraded_level > 0)
            out["degraded_level"] = float(self._degraded_level)
            out["healthy"] = float(self._healthy)
            if self._occ_slots:
                out["batch_occupancy"] = round(
                    self._occ_sum / self._occ_slots, 4)
            arms = dict(self._arms)
        for name, h in (("queue", self.queue_ms),
                        ("device", self.device_ms),
                        ("e2e", self.e2e_ms)):
            for k, v in h.snapshot().items():
                out[f"{name}_{k}"] = v
        if arms:
            out["arms"] = {a: st.snapshot() for a, st in sorted(arms.items())}
        return out

    def prom_families(self, labels: str = ""):
        """Every metric family as ``(name, type, [sample, ...])`` with
        ``labels`` (e.g. ``'model="minet"'``) merged into every sample
        — the unit a fleet aggregator merges across replicas so each
        family keeps ONE ``# TYPE`` line no matter how many labeled
        series export it (``merge_prom_families``).  Per-arm families
        carry ``labels`` + their ``arm=`` label."""
        with self._lock:
            counts = dict(self._counts)
            gauges = {
                "dsod_serve_queue_depth": self._queue_depth,
                "dsod_serve_inflight": self._inflight,
                "dsod_serve_degraded": int(self._degraded_level > 0),
                "dsod_serve_degraded_level": self._degraded_level,
                "dsod_serve_healthy": int(self._healthy),
            }
            occ = (self._occ_sum, self._occ_slots)
            arms = sorted(self._arms.items())
        sb = f"{{{labels}}}" if labels else ""
        fams = []
        for k, v in sorted(counts.items()):
            name = f"dsod_serve_{k}_total"
            fams.append((name, "counter", [f"{name}{sb} {v}"]))
        for name, v in sorted(gauges.items()):
            fams.append((name, "gauge", [f"{name}{sb} {v}"]))
        fams.append(("dsod_serve_batch_occupancy_sum", "counter",
                     [f"dsod_serve_batch_occupancy_sum{sb} {occ[0]}"]))
        fams.append(("dsod_serve_batch_slots_sum", "counter",
                     [f"dsod_serve_batch_slots_sum{sb} {occ[1]}"]))
        for name, h in (("dsod_serve_queue_latency_ms", self.queue_ms),
                        ("dsod_serve_device_latency_ms", self.device_ms),
                        ("dsod_serve_e2e_latency_ms", self.e2e_ms)):
            fams.append((name, "histogram",
                         h.prom_lines(name, labels=labels,
                                      include_type=False)))
        # Per-arm families: every arm's sample in ONE family group.
        counters = []
        for a, st in arms:
            with st._lock:
                counters.append((a, st._served, st._occ_sum, st._occ_slots))
        def arm_labels(a):
            return _merge_labels(labels, 'arm="' + a + '"')

        if counters:
            fams.append(("dsod_serve_arm_served_total", "counter", [
                'dsod_serve_arm_served_total{%s} %s'
                % (arm_labels(a), served)
                for a, served, _o, _s in counters]))
            fams.append(("dsod_serve_arm_batch_occupancy_sum", "counter", [
                'dsod_serve_arm_batch_occupancy_sum{%s} %s'
                % (arm_labels(a), occ_sum)
                for a, _served, occ_sum, _s in counters]))
            fams.append(("dsod_serve_arm_batch_slots_sum", "counter", [
                'dsod_serve_arm_batch_slots_sum{%s} %s'
                % (arm_labels(a), occ_slots)
                for a, _served, _o, occ_slots in counters]))
        for fam_name, attr in (("dsod_serve_arm_device_latency_ms",
                                "device_ms"),
                               ("dsod_serve_arm_e2e_latency_ms", "e2e_ms")):
            samples = []
            for a, st in arms:
                samples += getattr(st, attr).prom_lines(
                    fam_name, labels=arm_labels(a), include_type=False)
            if samples:
                fams.append((fam_name, "histogram", samples))
        return fams

    def render_prometheus(self, labels: str = "") -> str:
        """The /metrics payload (Prometheus text exposition format);
        ``labels`` rides every sample (fleet replicas pass their
        ``model=`` key)."""
        return render_prom_families(self.prom_families(labels))


class TelemetryRegistry:
    """Named providers of Prometheus families behind ONE render path.

    Both telemetry surfaces — the serve /metrics endpoints and the
    trainer sidecar — register ``provider(labels) -> families``
    callables here and render through the same
    ``merge_prom_families`` + ``render_prom_families`` machinery, so
    the TYPE-once-per-family discipline (and any future exposition
    change) cannot drift between the two stacks.  With a single
    provider the output is byte-identical to rendering that provider
    directly (merge of one group is the identity).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._providers = []  # (name, provider)

    def register(self, name: str, provider) -> "TelemetryRegistry":
        """``provider(labels: str) -> [(family, type, samples), ...]``.
        Registration order is render order."""
        with self._lock:
            if any(n == name for n, _p in self._providers):
                raise ValueError(f"telemetry provider {name!r} already "
                                 "registered")
            self._providers.append((name, provider))
        return self

    def prom_families(self, labels: str = ""):
        with self._lock:
            providers = list(self._providers)
        return merge_prom_families([p(labels) for _n, p in providers])

    def render(self, labels: str = "") -> str:
        """The /metrics payload (Prometheus text exposition format)."""
        return render_prom_families(self.prom_families(labels))


class MetricWriter:
    """Rank-0-gated scalar writer over clu.metric_writers.

    ``backend`` names what is actually writing (``clu`` | ``noop``):
    when clu is not importable the writer degrades to a LOGGED no-op
    (once per process, not per construction) instead of a silent one —
    a run that thinks it is writing TensorBoard curves but isn't is a
    debugging trap — and the trainer telemetry sidecar surfaces the
    active backend in /metrics
    (``dsod_train_metric_writer_info{backend=...}``).
    """

    _warned_missing_clu = False  # process-wide: log the fallback ONCE

    def __init__(self, logdir: Optional[str]):
        self._writer = None
        self.backend = "noop"
        if logdir and is_primary_process():
            try:
                from clu import metric_writers
            except ImportError:
                if not MetricWriter._warned_missing_clu:
                    MetricWriter._warned_missing_clu = True
                    get_logger().warning(
                        "clu is not installed — TensorBoard metric "
                        "writing is DISABLED (scalars still stream to "
                        "the log and the telemetry sidecar); pip "
                        "install clu to restore event files")
                return
            self._writer = metric_writers.create_default_writer(
                logdir, asynchronous=True)
            self.backend = "clu"

    def scalars(self, step: int, values: Dict[str, float]) -> None:
        if self._writer is not None:
            self._writer.write_scalars(
                int(step),
                {k: float(v) for k, v in values.items()
                 if isinstance(v, (int, float))})

    def flush(self) -> None:
        if self._writer is not None:
            self._writer.flush()

    def close(self) -> None:
        if self._writer is not None:
            self._writer.close()
            self._writer = None


@contextlib.contextmanager
def profile_window(logdir: Optional[str]):
    """Trace everything inside the with-block to ``logdir`` (no-op when
    logdir is falsy)."""
    if not logdir:
        yield
        return
    import jax

    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        get_logger().info("profiler trace written to %s", logdir)


class PreemptionGuard:
    """Install SIGTERM/SIGINT handlers that request a graceful stop.

    The train loop polls ``should_stop`` once per step; on True it saves
    a final checkpoint and returns instead of dying mid-epoch.
    """

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT)):
        self._stop = False
        self._prev = {}
        self._signals = signals

    def __enter__(self):
        for s in self._signals:
            try:
                self._prev[s] = signal.signal(s, self._handler)
            except ValueError:  # non-main thread (tests)
                pass
        return self

    def __exit__(self, *exc):
        for s, prev in self._prev.items():
            signal.signal(s, prev)
        return False

    def _handler(self, signum, frame):
        get_logger().warning(
            "signal %s: finishing step, checkpointing, exiting", signum)
        self._stop = True

    @property
    def should_stop(self) -> bool:
        """Host-local flag; on multi-host pods use :meth:`sync` so every
        worker leaves the collective train loop on the same step."""
        return self._stop

    def sync(self) -> bool:
        """Cross-host agreement: True iff ANY process saw a signal.

        Preemption typically SIGTERMs a single worker; if only that
        worker broke out of the loop, the rest would still be inside the
        train step's collectives and the final (collective) checkpoint
        save would deadlock.  Cheap (one tiny allgather) relative to a
        train step; skipped entirely in the single-process case.
        """
        import jax

        if jax.process_count() == 1:
            return self._stop
        import numpy as np
        from jax.experimental import multihost_utils

        flags = multihost_utils.process_allgather(
            np.asarray([self._stop], np.int32))
        return bool(np.asarray(flags).any())
