"""Observability: metric writers, profiler traces, preemption handling.

SURVEY.md §5 rows "tracing/profiling", "metrics/logging" and "failure
detection": the reference had a rank-0 file/console logger + TensorBoard
and nothing for preemption beyond --resume restarts.  TPU-native forms:

- ``MetricWriter``: clu.metric_writers (TensorBoard event files) on the
  primary process, no-op elsewhere — scalars stream from the train loop.
- ``profile_window``: ``jax.profiler`` trace of a step range; the dump
  opens in TensorBoard/Perfetto and shows per-HLO timing on device.
- ``PreemptionGuard``: SIGTERM/SIGINT → finish the current step, write
  a final checkpoint, exit 0.  TPU pods are preemptible by design; a
  final-checkpoint-on-SIGTERM is the idiomatic elasticity story (the
  next run --resume's from it).
"""

from __future__ import annotations

import contextlib
import signal
import threading
from typing import Dict, Optional

from .logging import get_logger, is_primary_process


class PipelineStats:
    """Thread-safe counters/gauges for the host data plane.

    Every blocking point in the input pipeline (data/pipeline.py)
    reports here, so "the step is input-bound" is a measured number
    instead of a guess.  Counters (cumulative):

    - ``data_starved_ms``   — consumer blocked on an empty prefetch
      queue: device idle waiting for data.  THE input-bound signal.
    - ``data_h2d_ms``       — time inside device_put / global array
      assembly on the H2D thread.
    - ``data_prefetch_full_ms`` — H2D thread blocked on a full queue
      (healthy: the step, not the input, is the bottleneck).
    - ``data_build_wait_ms`` — loader blocked waiting for a batch
      build worker (decode+augment stage is the bottleneck).
    - ``data_ring_wait_ms`` — builders blocked waiting for a free
      batch buffer (consumer holding the ring; raise ring_buffers).
    - ``data_batches``      — batches produced.

    Queue depth is tracked as a running (sum, count) pair and reported
    as ``data_queue_depth_avg`` / ``data_queue_size``.

    ``delta()`` returns metrics accumulated since the previous
    ``delta()`` call — the train loop calls it once per logging
    interval and hands the result to :class:`MetricWriter`, so the
    TensorBoard curves are per-interval, not monotone totals.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._counts: Dict[str, float] = {}
        self._last: Dict[str, float] = {}
        self._depth_sum = 0.0
        self._depth_n = 0
        self._depth_size = 0

    def add(self, key: str, value: float) -> None:
        with self._lock:
            self._counts[key] = self._counts.get(key, 0.0) + float(value)

    def observe_depth(self, depth: int, size: int) -> None:
        with self._lock:
            self._depth_sum += depth
            self._depth_n += 1
            self._depth_size = size

    def snapshot(self) -> Dict[str, float]:
        """Cumulative totals (plus average queue depth over the run)."""
        with self._lock:
            out = dict(self._counts)
            if self._depth_n:
                out["data_queue_depth_avg"] = self._depth_sum / self._depth_n
                out["data_queue_size"] = float(self._depth_size)
            return out

    def delta(self) -> Dict[str, float]:
        """Counters accumulated since the last ``delta()`` call."""
        with self._lock:
            out = {}
            for k, v in self._counts.items():
                out[k] = v - self._last.get(k, 0.0)
            self._last = dict(self._counts)
            if self._depth_n:
                out["data_queue_depth_avg"] = self._depth_sum / self._depth_n
                self._depth_sum = 0.0
                self._depth_n = 0
            return out


class MetricWriter:
    """Rank-0-gated scalar writer over clu.metric_writers."""

    def __init__(self, logdir: Optional[str]):
        self._writer = None
        if logdir and is_primary_process():
            from clu import metric_writers

            self._writer = metric_writers.create_default_writer(
                logdir, asynchronous=True)

    def scalars(self, step: int, values: Dict[str, float]) -> None:
        if self._writer is not None:
            self._writer.write_scalars(
                int(step),
                {k: float(v) for k, v in values.items()
                 if isinstance(v, (int, float))})

    def flush(self) -> None:
        if self._writer is not None:
            self._writer.flush()

    def close(self) -> None:
        if self._writer is not None:
            self._writer.close()
            self._writer = None


@contextlib.contextmanager
def profile_window(logdir: Optional[str]):
    """Trace everything inside the with-block to ``logdir`` (no-op when
    logdir is falsy)."""
    if not logdir:
        yield
        return
    import jax

    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        get_logger().info("profiler trace written to %s", logdir)


class PreemptionGuard:
    """Install SIGTERM/SIGINT handlers that request a graceful stop.

    The train loop polls ``should_stop`` once per step; on True it saves
    a final checkpoint and returns instead of dying mid-epoch.
    """

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT)):
        self._stop = False
        self._prev = {}
        self._signals = signals

    def __enter__(self):
        for s in self._signals:
            try:
                self._prev[s] = signal.signal(s, self._handler)
            except ValueError:  # non-main thread (tests)
                pass
        return self

    def __exit__(self, *exc):
        for s, prev in self._prev.items():
            signal.signal(s, prev)
        return False

    def _handler(self, signum, frame):
        get_logger().warning(
            "signal %s: finishing step, checkpointing, exiting", signum)
        self._stop = True

    @property
    def should_stop(self) -> bool:
        """Host-local flag; on multi-host pods use :meth:`sync` so every
        worker leaves the collective train loop on the same step."""
        return self._stop

    def sync(self) -> bool:
        """Cross-host agreement: True iff ANY process saw a signal.

        Preemption typically SIGTERMs a single worker; if only that
        worker broke out of the loop, the rest would still be inside the
        train step's collectives and the final (collective) checkpoint
        save would deadlock.  Cheap (one tiny allgather) relative to a
        train step; skipped entirely in the single-process case.
        """
        import jax

        if jax.process_count() == 1:
            return self._stop
        import numpy as np
        from jax.experimental import multihost_utils

        flags = multihost_utils.process_allgather(
            np.asarray([self._stop], np.int32))
        return bool(np.asarray(flags).any())
