"""Ring attention — sequence-parallel exact attention over the ``seq`` axis.

Long-context support (SURVEY.md §5 notes the 320×320 CNN zoo never needs
a sequence axis; this module exists so the transformer path — Swin-SOD
at high resolution, or any future ViT-style member — scales past
single-chip memory the TPU-native way, per PAPERS.md's blockwise /
ring-attention lineage).

Design (TPU-first):
- Each of the ``seq`` devices holds one contiguous block of queries,
  keys and values.  K/V blocks rotate around the ring with
  ``lax.ppermute`` (a pure ICI neighbour exchange — no all-gather, so
  per-chip memory stays O(N/n)) while every device accumulates its
  queries' attention over each visiting block.
- Numerically stable online softmax (running max / numerator /
  denominator, flash-attention style) in float32, inputs bf16-friendly.
- The loop is ``lax.fori_loop`` with a statically-known permutation, so
  XLA overlaps each block's einsum with the next ppermute (compute
  hides the communication, the standard ring-attention win).

Exactness: for any block partition, the result equals full softmax
attention — verified in tests against a single-device oracle.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from ..utils.compat import axis_size, shard_map


def resolve_attn_fn(attn_impl: str, causal: bool = False):
    """The single attn_impl → dense-attention-callable dispatch, shared
    by ``ulysses_attention``, ``ViTSOD``'s default core, and (for
    validation) the ring: 'xla' materializes scores, 'flash' is the
    Pallas kernel (non-causal only).  Raises the one canonical error
    for anything else."""
    if attn_impl == "flash":
        if causal:
            raise ValueError(
                "attn_impl='flash' has no causal mask; use the xla core")
        from ..pallas.flash_attention import flash_attention

        return flash_attention
    if attn_impl == "xla":
        return partial(full_attention, causal=causal) if causal \
            else full_attention
    raise ValueError(
        f"attn_impl must be 'xla' or 'flash', got {attn_impl!r}")


def _block_attend(q, k, v, *, scale, mask=None):
    """One block pair: returns (numerator, denominator, block_max).

    q: [B,H,Nq,D]; k/v: [B,H,Nk,D] → num [B,H,Nq,D], den/max [B,H,Nq].
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if mask is not None:
        s = jnp.where(mask, s, -jnp.inf)
    m = jnp.max(s, axis=-1)
    # All-masked rows: keep the running stats neutral (exp(-inf)=0).
    m_safe = jnp.where(jnp.isfinite(m), m, -jnp.inf)
    p = jnp.exp(s - jnp.where(jnp.isfinite(m), m, 0.0)[..., None])
    num = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    den = jnp.sum(p, axis=-1)
    return num, den, m_safe


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str = "seq",
    causal: bool = False,
    attn_impl: str = "xla",
) -> jnp.ndarray:
    """Exact attention with K/V ring-rotated over ``axis_name``.

    Call inside ``shard_map`` with the sequence dim sharded over
    ``axis_name``.  q/k/v: [B, H, N_local, D] (heads-major NHD layout);
    returns [B, H, N_local, D] in q's dtype.

    ``causal`` masks by *global* position: block offsets are derived
    from ``lax.axis_index``, so tokens attend only to global positions
    ≤ their own.

    ``attn_impl='flash'`` computes each visiting block pair with the
    Pallas flash kernel (O(N_local·D) HBM per step instead of a
    materialized N_local² score tile) and merges per-block
    (out, lse) results — composition of the two memory levers: shard
    the sequence over chips, then tile it through VMEM within each.
    Non-causal only (the kernel has no causal mask).
    """
    resolve_attn_fn(attn_impl, causal=causal)  # one shared validation
    if attn_impl == "flash":
        return _ring_flash(q, k, v, axis_name)
    n_blocks = axis_size(axis_name)
    my_idx = lax.axis_index(axis_name)
    scale = 1.0 / (q.shape[-1] ** 0.5)
    qf = q.astype(jnp.float32)
    n_local = q.shape[2]

    perm = [(i, (i + 1) % n_blocks) for i in range(n_blocks)]

    def causal_mask(src_idx):
        # [Nq, Nk] of "query global pos >= key global pos".
        q_pos = my_idx * n_local + jnp.arange(n_local)[:, None]
        k_pos = src_idx * n_local + jnp.arange(n_local)[None, :]
        return (q_pos >= k_pos)[None, None]  # broadcast over B,H

    def fold(i, k_blk, v_blk, num, den, m):
        # Block i arrived from device (my_idx - i) around the ring.
        src = (my_idx - i) % n_blocks
        mask = causal_mask(src) if causal else None
        b_num, b_den, b_max = _block_attend(qf, k_blk, v_blk,
                                            scale=scale, mask=mask)
        new_m = jnp.maximum(m, b_max)
        corr_old = jnp.exp(m - new_m)
        corr_new = jnp.exp(b_max - new_m)
        num = num * corr_old[..., None] + b_num * corr_new[..., None]
        den = den * corr_old + b_den * corr_new
        return num, den, new_m

    def body(i, carry):
        k_blk, v_blk, num, den, m = carry
        num, den, m = fold(i, k_blk, v_blk, num, den, m)
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        return k_blk, v_blk, num, den, m

    b, h, _, d = q.shape
    init = (
        k, v,
        jnp.zeros((b, h, n_local, d), jnp.float32),
        jnp.zeros((b, h, n_local), jnp.float32),
        jnp.full((b, h, n_local), -jnp.inf, jnp.float32),
    )
    # Rotate only n_blocks-1 times: the last visiting block is folded
    # in outside the loop — its ppermute result would be discarded, and
    # a collective can't be DCE'd, so it would be pure wasted ICI.
    k_l, v_l, num, den, m = lax.fori_loop(0, n_blocks - 1, body, init)
    num, den, m = fold(n_blocks - 1, k_l, v_l, num, den, m)
    out = num / jnp.maximum(den, 1e-30)[..., None]
    # Rows that attended to nothing (fully masked) return zeros.
    out = jnp.where(jnp.isfinite(m)[..., None], out, 0.0)
    return out.astype(q.dtype)


def _ring_flash(q, k, v, axis_name: str) -> jnp.ndarray:
    """Flash-kernel ring body: each visiting K/V block is attended with
    ``pallas.flash_attention_with_lse`` and folded into the running
    result by lse-weighted merge — algebraically the same online
    softmax as the xla body, just with the per-block inner loop pushed
    into VMEM.  Exact vs ``full_attention`` (tests)."""
    from ..pallas.flash_attention import flash_attention_with_lse

    n_blocks = axis_size(axis_name)
    perm = [(i, (i + 1) % n_blocks) for i in range(n_blocks)]
    b, h, n_local, d = q.shape

    def fold(k_blk, v_blk, out, lse):
        o_b, lse_b = flash_attention_with_lse(q, k_blk, v_blk)
        m = jnp.maximum(lse, lse_b)
        w_prev = jnp.exp(lse - m)          # 0 on the first visit
        w_blk = jnp.exp(lse_b - m)
        den = w_prev + w_blk
        out = (out * w_prev[..., None]
               + o_b.astype(jnp.float32) * w_blk[..., None]) / den[..., None]
        return out, m + jnp.log(den)

    def body(i, carry):
        k_blk, v_blk, out, lse = carry
        out, lse = fold(k_blk, v_blk, out, lse)
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        return k_blk, v_blk, out, lse

    init = (k, v,
            jnp.zeros((b, h, n_local, d), jnp.float32),
            jnp.full((b, h, n_local), -jnp.inf, jnp.float32))
    # Same n_blocks-1 rotation structure as the xla body: the final
    # visiting block folds in without a dead trailing ppermute.
    k_l, v_l, out, lse = lax.fori_loop(0, n_blocks - 1, body, init)
    out, _ = fold(k_l, v_l, out, lse)
    return out.astype(q.dtype)


def full_attention(q, k, v, causal: bool = False) -> jnp.ndarray:
    """Single-device oracle with the same [B,H,N,D] layout."""
    scale = 1.0 / (q.shape[-1] ** 0.5)
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        n, kn = q.shape[2], k.shape[2]
        mask = jnp.arange(n)[:, None] >= jnp.arange(kn)[None, :]
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)).astype(q.dtype)


def make_ring_attention_fn(mesh, causal: bool = False,
                           attn_impl: str = "xla"):
    """jit(shard_map(...)) wrapper: global [B,H,N,D] arrays sharded on
    N over the mesh's ``seq`` axis; drop-in replacement for
    ``full_attention`` at pod scale."""
    from jax.sharding import PartitionSpec as P

    spec = P(None, None, "seq", None)

    def fn(q, k, v):
        return ring_attention(q, k, v, axis_name="seq", causal=causal,
                              attn_impl=attn_impl)

    sharded = shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                            out_specs=spec, check_vma=False)
    return jax.jit(sharded)
