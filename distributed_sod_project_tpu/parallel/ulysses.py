"""Ulysses sequence parallelism — all-to-all head redistribution.

The second SP strategy next to ``ring_attention`` (SURVEY.md §2.3/§5
long-context).  Where the ring rotates K/V blocks around the ``seq``
axis (n_blocks-1 neighbour ppermutes, score tiles never leave the
chip), Ulysses re-shards ONCE: an all-to-all converts
sequence-sharding into head-sharding, every device then attends the
FULL sequence for its subset of heads, and a second all-to-all
converts back.  Trade-offs, honestly:

- ring: any head count, O(blocks) exchanges that overlap with compute,
  per-step traffic 2·(N/s)·D·(s−1)/s per head — the right shape when
  ICI latency hides under per-block compute.
- ulysses: exactly two all-to-alls (lower latency at moderate ``seq``),
  but needs ``heads % seq == 0``, and each device holds the full
  sequence for H/s heads — activation memory O(N·H/s·D), same total as
  the ring.  The full-length sequence per head is also the best shape
  for the Pallas flash kernel (long q/kv tiles instead of ring-block
  slivers), so ``attn_impl='flash'`` composes here too.

Both are exact: outputs equal single-device full attention to fp
round-off (tests/test_ulysses.py).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from .ring_attention import resolve_attn_fn
from ..utils.compat import axis_size, shard_map


def ulysses_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str = "seq",
    causal: bool = False,
    attn_impl: str = "xla",
) -> jnp.ndarray:
    """All-to-all sequence-parallel exact attention.

    Call inside ``shard_map`` with the sequence dim sharded over
    ``axis_name``.  q/k/v: [B, H, N_local, D] (heads-major, the
    ``ring_attention`` layout); returns the same shape/dtype.
    Requires ``H % axis_size == 0``.
    """
    s = axis_size(axis_name)
    h = q.shape[1]
    if h % s:
        raise ValueError(
            f"ulysses needs heads % seq == 0, got heads={h} seq={s} "
            "(use the ring strategy for non-dividing head counts)")

    def to_heads(t):
        # [B, H, N/s, D] -> [B, H/s, N, D]; all_to_all concatenates in
        # source-device order, so global token order is preserved.
        return lax.all_to_all(t, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    def to_seq(t):
        return lax.all_to_all(t, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    qg, kg, vg = to_heads(q), to_heads(k), to_heads(v)
    og = resolve_attn_fn(attn_impl, causal=causal)(qg, kg, vg)
    return to_seq(og)


def make_ulysses_attention_fn(mesh, causal: bool = False,
                              attn_impl: str = "xla"):
    """jit(shard_map(...)) wrapper mirroring
    ``ring_attention.make_ring_attention_fn``."""
    import jax
    from jax.sharding import PartitionSpec as P

    spec = P(None, None, "seq", None)

    def fn(q, k, v):
        return ulysses_attention(q, k, v, axis_name="seq", causal=causal,
                                 attn_impl=attn_impl)

    sharded = shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                            out_specs=spec, check_vma=False)
    return jax.jit(sharded)
