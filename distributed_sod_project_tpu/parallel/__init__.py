from .mesh import (
    MeshAxes,
    make_mesh,
    batch_spec,
    replicated_spec,
    batch_sharding,
    replicated_sharding,
    host_shard,
    global_batch_array,
)

__all__ = [
    "MeshAxes",
    "make_mesh",
    "batch_spec",
    "replicated_spec",
    "batch_sharding",
    "replicated_sharding",
    "host_shard",
    "global_batch_array",
]
