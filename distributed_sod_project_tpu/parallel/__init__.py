from .mesh import (
    MeshAxes,
    make_mesh,
    batch_spec,
    replicated_spec,
    batch_sharding,
    replicated_sharding,
    host_shard,
    global_batch_array,
)
from .sp import make_sp_eval_step, make_sp_train_step, sp_batch_sharding
from .ulysses import make_ulysses_attention_fn, ulysses_attention
from .tp import (
    DEFAULT_TP_RULES,
    SWIN_TP_RULES,
    VIT_TP_RULES,
    make_tp_train_step,
    param_partition_specs,
    shard_state,
    state_partition_specs,
)

__all__ = [
    "MeshAxes",
    "make_mesh",
    "batch_spec",
    "replicated_spec",
    "batch_sharding",
    "replicated_sharding",
    "host_shard",
    "global_batch_array",
    "DEFAULT_TP_RULES",
    "VIT_TP_RULES",
    "make_sp_eval_step",
    "make_sp_train_step",
    "make_ulysses_attention_fn",
    "ulysses_attention",
    "sp_batch_sharding",
    "SWIN_TP_RULES",
    "make_tp_train_step",
    "param_partition_specs",
    "shard_state",
    "state_partition_specs",
]
