from .mesh import (
    MeshAxes,
    make_mesh,
    batch_spec,
    replicated_spec,
    batch_sharding,
    replicated_sharding,
    host_shard,
    global_batch_array,
)
from .tp import (
    SWIN_TP_RULES,
    make_tp_train_step,
    param_partition_specs,
    shard_state,
    state_partition_specs,
)

__all__ = [
    "MeshAxes",
    "make_mesh",
    "batch_spec",
    "replicated_spec",
    "batch_sharding",
    "replicated_sharding",
    "host_shard",
    "global_batch_array",
    "SWIN_TP_RULES",
    "make_tp_train_step",
    "param_partition_specs",
    "shard_state",
    "state_partition_specs",
]
