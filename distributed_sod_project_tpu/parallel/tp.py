"""Tensor parallelism over the ``model`` mesh axis — GSPMD style.

The DP train step (train/step.py) is shard_map-manual because it needs
named-axis BatchNorm psums.  The transformer path (Swin-SOD: LayerNorm
only, no cross-replica BN) takes the other TPU-idiomatic route instead:
**annotate parameter shardings, jit, and let XLA's SPMD partitioner
insert the collectives** (the scaling-book recipe; SURVEY.md §2.3 "TP"
row).  Megatron-style layout:

- qkv / MLP-up ``Dense`` kernels are column-parallel — output features
  sharded over ``model`` — so each chip computes its slice of the heads
  with zero communication;
- attention-out / MLP-down kernels are row-parallel — input features
  sharded — so XLA emits exactly one reduce(-scatter)/all-reduce pair
  per block, the Megatron minimum;
- the relative-position bias table shards over its heads column;
- everything else (LayerNorms, patch-merge projections, conv decoder)
  stays replicated over ``model`` and batch-sharded compute rides the
  ``data`` axis exactly as in the DP step (gradient allreduce over
  ``data`` is inserted by the partitioner, replacing step.py's explicit
  ``pmean``).

Sharding a leaf is skipped (replicated) when its dimension does not
divide the axis size, so the same rules work for any ``model`` degree
that divides the widths — degrees that do not divide simply fall back
per-leaf.

Alignment note: Swin packs q/k/v into one fused ``Dense(3d)`` whose
output columns are ordered HEAD-major — (heads, 3, hd), a deliberate
departure from the official (3, heads, hd) checkpoints (the weight
porter permutes them) — so a column shard of the packed axis lands on
complete per-head (q,k,v) triples whenever ``model`` divides the
stage's head count (heads % model == 0).  Measured on the (data=4, model=2) compiled train
step: 116 → 16 all-gathers vs the qkv-major packing
(tests/test_tensor_parallel.py::test_tp_step_avoids_qkv_resharding).
Stage 1 of Swin-T has 3 heads, which does not divide model=2 — GSPMD
reshards just that stage, keeping the math exact.  ViT-SOD uses
separate head-aligned q/k/v projections (``VIT_TP_RULES``), and fit()
enforces its ``heads % model == 0`` precondition.
"""

from __future__ import annotations

import re
from typing import Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# (path regex, spec) — first match wins; paths are '/'-joined key paths.
# Swin modules: SwinBlock's direct Dense_0/Dense_1 are the MLP up/down;
# WindowAttention's Dense_0/Dense_1 are qkv / output projection.
SWIN_TP_RULES: Tuple[Tuple[str, P], ...] = (
    (r"WindowAttention_\d+/Dense_0/kernel$", P(None, "model")),
    (r"WindowAttention_\d+/Dense_0/bias$", P("model")),
    (r"WindowAttention_\d+/Dense_1/kernel$", P("model", None)),
    (r"WindowAttention_\d+/rel_pos_bias$", P(None, "model")),
    (r"SwinBlock_\d+/Dense_0/kernel$", P(None, "model")),
    (r"SwinBlock_\d+/Dense_0/bias$", P("model")),
    (r"SwinBlock_\d+/Dense_1/kernel$", P("model", None)),
)

# ViT-SOD blocks (models/vit_sod.py::_Block): separate q/k/v
# projections column-shard head-aligned (heads % model == 0), proj /
# mlp_down row-shard — same Megatron layout, one allreduce pair per
# block.
VIT_TP_RULES: Tuple[Tuple[str, P], ...] = (
    (r"block\d+/(q|k|v)/kernel$", P(None, "model")),
    (r"block\d+/(q|k|v)/bias$", P("model")),
    (r"block\d+/proj/kernel$", P("model", None)),
    (r"block\d+/mlp_up/kernel$", P(None, "model")),
    (r"block\d+/mlp_up/bias$", P("model")),
    (r"block\d+/mlp_down/kernel$", P("model", None)),
)

# The regex namespaces are disjoint, so one combined default covers the
# whole transformer zoo — non-matching models simply replicate.
DEFAULT_TP_RULES: Tuple[Tuple[str, P], ...] = SWIN_TP_RULES + VIT_TP_RULES


def _leaf_path(path) -> str:
    parts = []
    for k in path:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        else:
            parts.append(str(k))
    return "/".join(parts)


def _divisible(shape, spec: P, mesh: Mesh) -> bool:
    axis_sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    for dim, names in zip(shape, spec):
        if names is None:
            continue
        names = names if isinstance(names, tuple) else (names,)
        total = int(np.prod([axis_sizes[n] for n in names]))
        if dim % total:
            return False
    return True


def param_partition_specs(params, mesh: Mesh,
                          rules: Sequence[Tuple[str, P]] = DEFAULT_TP_RULES):
    """Spec pytree for ``params``: first rule whose regex matches the
    '/'-joined path wins; non-matching (or non-divisible) leaves
    replicate.  Specs longer than the leaf's rank are an error caught
    here rather than inside jit."""
    compiled = [(re.compile(pat), spec) for pat, spec in rules]

    def assign(path, leaf):
        name = _leaf_path(path)
        for pat, spec in compiled:
            if pat.search(name):
                if len(spec) > leaf.ndim:
                    raise ValueError(
                        f"rule {pat.pattern!r} spec {spec} exceeds rank "
                        f"of {name} {leaf.shape}")
                if _divisible(leaf.shape, spec, mesh):
                    return spec
                return P()
        return P()

    return jax.tree_util.tree_map_with_path(assign, params)


def _specs_like(tree, params_treedef, param_specs):
    """Spec tree for an arbitrary container (e.g. an optax state):
    any subtree whose treedef equals the params' gets ``param_specs``
    (momentum/EMA buffers shard with their parameters); all other
    leaves replicate."""

    def rec(t):
        try:
            if jax.tree_util.tree_structure(t) == params_treedef:
                return param_specs
        except Exception:
            pass
        if isinstance(t, tuple) and hasattr(t, "_fields"):  # NamedTuple
            return type(t)(*(rec(x) for x in t))
        if isinstance(t, (tuple, list)):
            return type(t)(rec(x) for x in t)
        if isinstance(t, dict):
            return {k: rec(v) for k, v in t.items()}
        return P()

    return rec(tree)


def _zero1_specs(params, param_specs, mesh: Mesh):
    """Cross-replica weight-update sharding (PAPERS.md: arXiv
    2004.13336, ZeRO-1 style): optimizer/EMA buffers shard over ``data``
    so each replica stores and updates 1/N of them — the SPMD
    partitioner turns the gradient allreduce into reduce-scatter +
    sharded update + param all-gather.  A leaf takes ``data`` on its
    first divisible dim; leaves already sharded by TP rules keep them."""
    n_data = mesh.shape.get("data", 1)

    def assign(leaf, spec: P):
        if spec != P():
            return spec  # TP-sharded: leave the Megatron layout alone
        for dim, size in enumerate(leaf.shape):
            if size % n_data == 0 and size >= n_data:
                return P(*([None] * dim + ["data"]))
        return P()

    return jax.tree_util.tree_map(
        assign, params, param_specs,
        is_leaf=lambda x: isinstance(x, P))


def state_partition_specs(state, mesh: Mesh,
                          rules: Sequence[Tuple[str, P]] = DEFAULT_TP_RULES,
                          zero1: bool = False):
    """A TrainState-shaped pytree of PartitionSpecs: params per the TP
    rules, optimizer buffers matching their parameters (or sharded over
    ``data`` with ``zero1``), the rest replicated."""
    param_specs = param_partition_specs(state.params, mesh, rules)
    pdef = jax.tree_util.tree_structure(state.params)
    buf_specs = (_zero1_specs(state.params, param_specs, mesh)
                 if zero1 else param_specs)
    return type(state)(
        step=P(),
        params=param_specs,
        batch_stats=jax.tree_util.tree_map(lambda _: P(), state.batch_stats),
        opt_state=_specs_like(state.opt_state, pdef, buf_specs),
        ema_params=buf_specs if state.ema_params is not None else None,
    )


def to_shardings(spec_tree, mesh: Mesh):
    """PartitionSpec pytree → NamedSharding pytree (specs are tuple
    subclasses, so tree_map needs the is_leaf guard)."""
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), spec_tree,
        is_leaf=lambda x: isinstance(x, P))


def shard_state(state, mesh: Mesh,
                rules: Sequence[Tuple[str, P]] = DEFAULT_TP_RULES,
                zero1: bool = False):
    """Place a host/replicated TrainState onto the mesh with the TP
    (and optionally ZeRO-1) layout; returns (sharded_state,
    state_shardings)."""
    shardings = to_shardings(
        state_partition_specs(state, mesh, rules, zero1=zero1), mesh)
    return jax.device_put(state, shardings), shardings
