"""The partition-rule layer — regex rules over param tree paths → specs.

ROADMAP item 1: DP, TP, and SP were three hand-built step builders that
every feature had to be threaded through three times.  This module is
the declarative half of their replacement (parallel/engine.py is the
step-builder half): a rule is ``(path regex, PartitionSpec)``, a rule
TABLE is matched first-wins over the '/'-joined tree path of every
parameter (the SNIPPETS.md [1]/[2] ``TreePathShardingRule`` /
``FSDPShardingRule`` + ``named_tree_map`` idiom), and the three
parallelism modes collapse into PRESETS — rule tables plus a little
metadata the engine threads into ONE traced step:

- ``dp``  — everything replicated over ``model``/``seq``; batch rides
  ``data`` under shard_map (named-axis SyncBN + explicit grad psum);
- ``tp``  — the Megatron tables from parallel/tp.py (column/row Dense
  shards over ``model``), GSPMD jit-with-shardings;
- ``sp``  — replicated params, batch sharded ``('data', 'seq')``,
  ring/ulysses attention (vit_sod only).

On top of the tables, two rule TRANSFORMS:

- ``fsdp_fallback_rule`` — FSDP-style auto-sharding of the largest
  divisible axis for leaves no explicit rule matched (the scalax
  ``FSDPShardingRule`` recipe);
- ``zero_state_specs`` — ZeRO-style weight-update sharding (PAPERS.md:
  arXiv 2004.13336): optimizer moments and EMA shard over ``data`` so
  each replica stores/updates 1/N of them, generalizing
  parallel/tp.py's ``_zero1_specs`` to the rules engine's
  ``parallel.zero`` levels.

Gradient-communication planning lives here too (``grad_buckets``): the
bucketed, backward-ordered allreduce partitions the flattened gradient
leaves — reversed, so the latest layers' grads (first available during
backward) reduce first — into size-targeted buckets, each its own
``lax.psum`` the engine emits.  Pure functions over shapes; the comm
ledger (utils/capacity.py) prices the resulting collectives.
"""

from __future__ import annotations

import re
from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from .tp import (DEFAULT_TP_RULES, SWIN_TP_RULES, VIT_TP_RULES,  # noqa: F401
                 _divisible, _leaf_path, _specs_like, to_shardings)

# Matches everything; the explicit spelling of "replicate the rest" so
# a strict table can end with it and still be total.
REPLICATE_REST: Tuple[str, P] = (r".*", P())

# Preset → parameter rule table.  DP and SP replicate every parameter
# (their non-data axes are degenerate / the batch axis does the work);
# TP is the Megatron layout.  FSDP's table is EMPTY on purpose: every
# leaf goes to ``fsdp_fallback_rule`` (largest divisible dim over
# ``data``), which IS the preset — params shard over data, the
# partitioner all-gathers them just-in-time per layer and
# reduce-scatters grads.  The tables are TOTAL only with the
# replicate-by-default fallback — strict matching surfaces the holes.
PRESET_PARAM_RULES = {
    "dp": (REPLICATE_REST,),
    "tp": DEFAULT_TP_RULES + (REPLICATE_REST,),
    "sp": (REPLICATE_REST,),
    "fsdp": (),
}


def named_tree_map(fn: Callable[[str, Any], Any], tree, *rest):
    """``tree_map`` with the '/'-joined key path as the first argument
    (the scalax/fmengine ``named_tree_map`` idiom): ``fn(path, leaf,
    *rest_leaves)`` per leaf."""
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf, *r: fn(_leaf_path(path), leaf, *r),
        tree, *rest)


def tree_paths(tree) -> List[str]:
    """The '/'-joined path of every leaf, in flatten order."""
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [_leaf_path(path) for path, _ in flat]


def fsdp_fallback_rule(mesh: Mesh, axis: str = "data",
                       min_leaf_size: int = 2 ** 14):
    """FSDP-style auto-sharding fallback: shard the LARGEST divisible
    dimension of a leaf over ``axis``; leaves smaller than
    ``min_leaf_size`` elements (biases, norms — where the sharding tax
    outweighs the bytes) and leaves with no divisible dim replicate.
    Returns ``fallback(path, leaf) -> PartitionSpec`` for
    ``match_partition_rules``."""
    n = mesh.shape.get(axis, 1)

    def fallback(path: str, leaf) -> P:
        del path
        if n <= 1 or int(np.prod(leaf.shape or (1,))) < min_leaf_size:
            return P()
        best_dim, best_size = -1, 0
        for dim, size in enumerate(leaf.shape):
            if size % n == 0 and size > best_size:
                best_dim, best_size = dim, size
        if best_dim < 0:
            return P()
        return P(*([None] * best_dim + [axis]))

    return fallback


def match_partition_rules(rules: Sequence[Tuple[str, P]], params,
                          mesh: Mesh, *, strict: bool = False,
                          fallback: Optional[Callable[[str, Any], P]] = None):
    """Spec pytree for ``params``: first rule whose regex matches the
    '/'-joined path wins (``re.search`` semantics, same as
    parallel/tp.py).  Unmatched leaves go to ``fallback(path, leaf)``
    when given, else replicate — unless ``strict``, which raises ONE
    error listing every unmatched path (the loud mode for authoring a
    new backbone's table).  Specs that exceed a leaf's rank raise at
    build time; specs whose sharded dims don't divide the mesh axis
    fall back per-leaf to ``P()`` (same contract the TP rules always
    had, so any ``model`` degree works)."""
    compiled = [(re.compile(pat), spec) for pat, spec in rules]
    unmatched: List[str] = []

    def assign(path: str, leaf) -> P:
        for pat, spec in compiled:
            if pat.search(path):
                if len(spec) > leaf.ndim:
                    raise ValueError(
                        f"rule {pat.pattern!r} spec {spec} exceeds rank "
                        f"of {path} {leaf.shape}")
                if _divisible(leaf.shape, spec, mesh):
                    return spec
                return P()
        unmatched.append(path)
        if fallback is not None:
            return fallback(path, leaf)
        return P()

    specs = named_tree_map(assign, params)
    if strict and unmatched:
        raise ValueError(
            f"{len(unmatched)} parameter path(s) matched by NO "
            f"partition rule (strict mode): {sorted(unmatched)[:8]}"
            + (" …" if len(unmatched) > 8 else ""))
    return specs


def zero_state_specs(params, param_specs, mesh: Mesh, axis: str = "data"):
    """ZeRO weight-update sharding specs for params-shaped buffers
    (optimizer moments, the MultiSteps accumulator, EMA): each leaf
    takes ``axis`` on its first divisible dim so every replica stores
    and updates 1/N of the buffer; leaves already sharded by explicit
    rules keep their layout (the TP Megatron shards ARE the buffer
    shards there).  Identical math to parallel/tp.py::_zero1_specs,
    exposed on the rules layer."""
    n = mesh.shape.get(axis, 1)

    def assign(leaf, spec: P) -> P:
        if spec != P():
            return spec
        for dim, size in enumerate(leaf.shape):
            if size % n == 0 and size >= n:
                return P(*([None] * dim + [axis]))
        return P()

    return jax.tree_util.tree_map(
        assign, params, param_specs, is_leaf=lambda x: isinstance(x, P))


def state_specs(state, mesh: Mesh, *,
                rules: Sequence[Tuple[str, P]] = DEFAULT_TP_RULES,
                zero: int = 0, strict: bool = False,
                fallback: Optional[Callable[[str, Any], P]] = None):
    """A TrainState-shaped spec tree from a rule table: params per the
    rules, optimizer buffers matching their parameters (or ZeRO-sharded
    over ``data`` with ``zero >= 1``), step/batch_stats replicated.
    The rules-engine generalization of tp.state_partition_specs."""
    param_specs = match_partition_rules(rules, state.params, mesh,
                                        strict=strict, fallback=fallback)
    pdef = jax.tree_util.tree_structure(state.params)
    buf_specs = (zero_state_specs(state.params, param_specs, mesh)
                 if zero >= 1 else param_specs)
    # The int8_ef error-feedback residual is per-replica by
    # construction (each replica's quantization error on ITS gradient
    # contribution): leading replica dim sharded over ``data`` — the
    # same weight-update-sharding axis the ZeRO buffers use.
    residual_specs = (P("data")
                     if getattr(state, "comm_residual", None) is not None
                     else None)
    return type(state)(
        step=P(),
        params=param_specs,
        batch_stats=jax.tree_util.tree_map(lambda _: P(),
                                           state.batch_stats),
        opt_state=_specs_like(state.opt_state, pdef, buf_specs),
        ema_params=buf_specs if state.ema_params is not None else None,
        comm_residual=residual_specs,
    )


def shard_state_by_rules(state, mesh: Mesh, *,
                         rules: Sequence[Tuple[str, P]] = DEFAULT_TP_RULES,
                         zero: int = 0,
                         fallback: Optional[Callable[[str, Any], P]] = None):
    """Place a host/replicated TrainState onto the mesh per the rule
    table (+ ZeRO buffer sharding; ``fallback`` for FSDP auto-sharding
    of unmatched leaves); returns (state, state_shardings)."""
    shardings = to_shardings(
        state_specs(state, mesh, rules=rules, zero=zero,
                    fallback=fallback), mesh)
    return jax.device_put(state, shardings), shardings


# -- gradient-communication planning (the bucketed allreduce) ---------

def grad_buckets(shapes_dtypes: Sequence[Tuple[Tuple[int, ...], Any]],
                 bucket_bytes: int) -> List[List[int]]:
    """Partition gradient leaves (given as (shape, dtype) in FLATTEN
    order) into size-targeted buckets in BACKWARD order — reversed
    flatten order, so the decoder/head grads that finish first during
    the backward pass land in the first bucket and their allreduce can
    overlap the encoder's remaining backward compute (the DDP bucketing
    recipe, PAPERS.md comm papers).

    Invariants (tests/test_sharding_rules.py): every leaf index appears
    in EXACTLY one bucket; bucket order is strictly descending leaf
    index at the boundaries; a bucket closes once it reaches
    ``bucket_bytes`` (so every bucket except possibly the last is at
    least the target).  ``bucket_bytes <= 0`` → one bucket (the
    monolithic reduce, spelled through the same code path)."""
    n = len(shapes_dtypes)
    if n == 0:
        return []
    if bucket_bytes <= 0:
        return [list(range(n - 1, -1, -1))]
    buckets: List[List[int]] = []
    cur: List[int] = []
    cur_bytes = 0
    for idx in range(n - 1, -1, -1):
        shape, dtype = shapes_dtypes[idx]
        nbytes = int(np.prod(shape or (1,))) * np.dtype(dtype).itemsize
        cur.append(idx)
        cur_bytes += nbytes
        if cur_bytes >= bucket_bytes:
            buckets.append(cur)
            cur, cur_bytes = [], 0
    if cur:
        buckets.append(cur)
    return buckets


def comm_residual_size(shapes_dtypes: Sequence[Tuple[Tuple[int, ...], Any]],
                       bucket_bytes: int) -> int:
    """Element count of the int8_ef error-feedback residual for a
    gradient tree: every leaf appears in exactly one bucket's wire
    buffer, so the residual is one flat f32 vector covering every
    element once, segments laid out in the deterministic
    bucket-then-dtype order ``bucketed_pmean`` iterates."""
    del bucket_bytes  # every leaf appears exactly once regardless
    return sum(int(np.prod(shape or (1,))) for shape, _ in shapes_dtypes)


def _hier_psum(vec, axis, hierarchy):
    """Two-level reduction of one flat wire buffer: intra-host
    reduce-scatter -> inter-host all-reduce on 1/chips_per_host of the
    bytes -> intra-host all-gather (the ICI x DCN recipe; PAPERS.md
    arXiv 1902.00465).  ``hierarchy`` is ``(intra_groups,
    inter_groups)`` from ``mesh.hier_data_groups``.  Computes the
    pair-tree association ``sum_hosts(sum_chips(x))`` — exact (bitwise
    the flat psum) for integer wire dtypes; for floats the association
    differs from XLA's flat fold at the last ulp.
    """
    from jax import lax
    import jax.numpy as jnp

    intra, inter = hierarchy
    chips = len(intra[0])
    n = vec.shape[0]
    pad = (-n) % chips
    if pad:
        vec = jnp.concatenate([vec, jnp.zeros((pad,), vec.dtype)])
    seg = lax.psum_scatter(vec, axis, scatter_dimension=0,
                           axis_index_groups=intra, tiled=True)
    seg = lax.psum(seg, axis, axis_index_groups=inter)
    full = lax.all_gather(seg, axis, axis_index_groups=intra,
                          tiled=True)
    return full[:n] if pad else full


def bucketed_pmean(grads, axis, bucket_bytes: int,
                   compression: str = "none", *,
                   hierarchy=None, residual=None):
    """Gradient mean over ``axis`` as one FUSED reduction per
    size-targeted bucket (backward-ordered; ``grad_buckets``): each
    bucket's leaves are raveled and concatenated into ONE flat buffer
    (the DDP flat-bucket recipe), reduced, then sliced back — so a
    B-bucket plan is exactly B 1-D collectives in the dumped HLO
    (the countable signal tools/hlo_guard.py's comm arm checks) instead
    of one per leaf, and early buckets can overlap remaining backward
    compute.

    Per element the arithmetic is EXACTLY what ``lax.pmean`` computes —
    psum then division by ``psum(1, axis)``; ravel/concat/slice touch
    no values — so with ``compression='none'`` the result is bitwise
    the monolithic pmean's (asserted in tests/test_sharding_rules.py).

    ``hierarchy=(intra_groups, inter_groups)`` replaces each bucket's
    flat psum with the two-level intra-host reduce-scatter -> inter-host
    all-reduce -> intra-host all-gather (``_hier_psum``), putting only
    1/chips_per_host of the bytes on the slow DCN hop.

    ``compression='bf16'`` casts each bucket's wire buffer to bfloat16
    and back after — half the gradient comm bytes, NOT bitwise.
    ``compression='int8_ef'`` adds the persistent ``residual`` (one
    flat f32 vector, segments in this function's bucket-then-dtype
    iteration order) into the buffer, quantizes symmetrically to int8
    against a GLOBAL scale (``lax.pmax`` of per-replica amax — a shared
    scale makes the integer psum exact and order-independent), keeps
    the per-replica quantization error as the next step's residual, and
    transports int32 on the wire (int8 payload; the ledger prices the
    achievable 1 B/elem).  Both gated by tools/grad_comm_gate.py's
    checked-in baseline.

    Returns the gradient tree, or ``(tree, new_residual)`` when
    ``residual`` is given (int8_ef error feedback).
    """
    import jax.numpy as jnp
    from jax import lax

    if compression == "int8_ef" and residual is None:
        raise ValueError(
            "grad_compression=int8_ef needs the error-feedback "
            "residual (state.comm_residual) threaded in")

    flat, treedef = jax.tree_util.tree_flatten(grads)
    buckets = grad_buckets([(g.shape, g.dtype) for g in flat],
                           bucket_bytes)
    denom = lax.psum(1, axis)

    def reduce_buf(v):
        if hierarchy is not None:
            return _hier_psum(v, axis, hierarchy)
        return lax.psum(v, axis)

    out: List[Any] = [None] * len(flat)
    res_out: List[Any] = []
    res_off = 0
    for bucket in buckets:
        # One flat buffer per (bucket, dtype) — a single buffer on the
        # homogeneous-f32 zoo; mixed-precision trees fuse per dtype.
        by_dtype: dict = {}
        for i in bucket:
            by_dtype.setdefault(jnp.dtype(flat[i].dtype), []).append(i)
        for dt, idxs in by_dtype.items():
            vec = jnp.concatenate([flat[i].reshape(-1) for i in idxs])
            if compression == "bf16":
                summed = reduce_buf(vec.astype(jnp.bfloat16)).astype(dt)
            elif compression == "int8_ef":
                seg = lax.dynamic_slice_in_dim(
                    residual, res_off, vec.shape[0])
                buf = vec.astype(jnp.float32) + seg
                amax = lax.pmax(jnp.max(jnp.abs(buf)), axis)
                scale = jnp.where(amax > 0, amax / 127.0,
                                  jnp.ones((), jnp.float32))
                q = jnp.clip(jnp.round(buf / scale), -127, 127)
                res_out.append(buf - q * scale)
                res_off += vec.shape[0]
                summed = (reduce_buf(q.astype(jnp.int32))
                          .astype(jnp.float32) * scale).astype(dt)
            else:
                summed = reduce_buf(vec)
            off = 0
            for i in idxs:
                n = int(np.prod(flat[i].shape or (1,)))
                out[i] = (summed[off:off + n].reshape(flat[i].shape)
                          / denom)
                off += n
    tree = jax.tree_util.tree_unflatten(treedef, out)
    if residual is None:
        return tree
    new_residual = (jnp.concatenate(res_out) if res_out
                    else jnp.zeros_like(residual))
    return tree, new_residual


def tree_bytes(tree) -> int:
    """Total bytes of a pytree's leaves (host or abstract arrays)."""
    return sum(int(np.prod(x.shape or (1,))) * np.dtype(x.dtype).itemsize
               for x in jax.tree_util.tree_leaves(tree))


def sharded_tree_bytes(tree, spec_tree, mesh: Mesh) -> int:
    """Per-device bytes of a pytree under a spec tree: each leaf's
    bytes divided by the product of its sharded mesh-axis sizes."""
    total = 0
    for leaf, spec in zip(
            jax.tree_util.tree_leaves(tree),
            jax.tree_util.tree_leaves(
                spec_tree, is_leaf=lambda x: isinstance(x, P))):
        nbytes = int(np.prod(leaf.shape or (1,))) * np.dtype(
            leaf.dtype).itemsize
        div = 1
        if isinstance(spec, P):
            for names in spec:
                if names is None:
                    continue
                names = names if isinstance(names, tuple) else (names,)
                div *= int(np.prod([mesh.shape[nm] for nm in names]))
        total += nbytes // max(div, 1)
    return total
