"""The unified rule-driven train-step builder (ROADMAP item 1).

ONE builder subsumes the three hand-built ones (train/step.py DP,
parallel/tp.py GSPMD, parallel/sp.py SP): a preset from
``parallel/rules.py`` decides the per-preset seams — RNG fold, forward/
loss path, gradient reduction, and trace wrapper — while every shared
seam (steps_per_dispatch chunking, grad accumulation, EMA,
skip_nonfinite, PR 10's ``maybe_health_metrics``, PR 11's
capacity-ledger compile hook via ``.lower``) is threaded exactly ONCE.

Bitwise contract: with ``grad_compression='none'`` and a flat (single
level) reduction, the built step is bitwise (f32, CPU) identical to the
legacy builder of the same preset — proven in round 17 against all
three, after which the default flipped and the legacy builders were
deleted (round 18); the bucketed reducer computes per element exactly
what ``lax.pmean`` computes (tests/test_sharding_rules.py asserts it,
tools/t1.sh re-proves a smoke every round).

Perf deliverables on top of the rule layer:

- ``parallel.preset=fsdp`` — full parameter sharding as pure config:
  params shard over ``data`` (``rules.fsdp_fallback_rule`` picks each
  leaf's largest divisible dim), the GSPMD partitioner all-gathers
  them just-in-time per layer in forward/backward and reduce-scatters
  grads; optimizer buffers inherit the param layout, so weight-update
  sharding comes free at any ``zero`` level.
- ``parallel.zero=1|2`` — ZeRO-style weight-update sharding: optimizer
  moments + EMA shard over ``data`` (GSPMD presets; grads
  reduce-scatter into 1/N updates, params all-gather), level 2
  additionally pins the gradient tree to the sharded layout.  HBM
  saving is priced by ``comm_plan`` and reported through the capacity
  ledger.
- ``parallel.comm_bucket_mb`` — bucketed, backward-ordered gradient
  allreduce on the DP preset (``rules.bucketed_pmean``): one
  ``lax.psum`` per size-targeted bucket so early buckets' communication
  overlaps remaining backward compute.
- ``mesh.data_hosts>1`` — two-level ICI x DCN reduction on the DP
  preset: each bucket's psum becomes intra-host reduce-scatter ->
  inter-host all-reduce on 1/chips_per_host of the bytes -> intra-host
  all-gather (``rules._hier_psum``; groups from
  ``mesh.hier_data_groups``).
- ``parallel.grad_compression=bf16|int8_ef`` — wire compression on the
  bucketed reducer; int8_ef carries a persistent error-feedback
  residual in the train state (``TrainState.comm_residual``, sharded
  over ``data``).  Both gated by tools/grad_comm_gate.py.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..losses import deep_supervision_loss
from ..train.state import TrainState
from ..train.step import (_loss_kwargs, apply_update, chunk_batch_spec,
                          chunked_step_fn, maybe_health_metrics,
                          maybe_remat, notfinite_count, rescale_batch,
                          resolve_remat_policy)
from ..utils.compat import shard_map
from . import rules as rules_mod
from .mesh import (batch_sharding, batch_spec, hier_data_groups,
                   replicated_sharding)

PRESETS = ("dp", "tp", "sp", "fsdp")


def select_preset(cfg, mesh: Mesh) -> str:
    """The rules-engine preset for a config+mesh: an explicit
    ``parallel.preset`` wins (``fsdp`` can only be asked for — nothing
    about a mesh implies it); ``auto`` derives the historical routing —
    ``sp`` when the ``seq`` axis is sharded, ``tp`` (the GSPMD preset)
    when the ``model`` axis is sharded or any ZeRO level is on, else
    ``dp``."""
    explicit = getattr(cfg.parallel, "preset", "auto")
    if explicit != "auto":
        return explicit
    if mesh.shape.get("seq", 1) > 1:
        return "sp"
    if (mesh.shape.get("model", 1) > 1 or cfg.optim.zero1
            or cfg.parallel.zero > 0):
        return "tp"
    return "dp"


def effective_zero(cfg) -> int:
    """The ZeRO level the engine runs at: ``parallel.zero``, with the
    legacy ``optim.zero1`` spelling mapped to level 1 (validate_parallel
    rejects both being set)."""
    return cfg.parallel.zero or (1 if cfg.optim.zero1 else 0)


def make_unified_train_step(
    model,
    loss_cfg,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    *,
    preset: str,
    schedule: Optional[optax.Schedule] = None,
    donate: bool = True,
    remat: bool = False,
    ema_decay: float = 0.0,
    scale_hw: Optional[Tuple[int, int]] = None,
    donate_batch: bool = False,
    remat_policy: str = "none",
    steps_per_dispatch: int = 1,
    health: bool = False,
    sp_strategy: str = "ring",
    state_shardings=None,
    zero: int = 0,
    comm_bucket_mb: float = 0.0,
    grad_compression: str = "none",
    data_hosts: int = 1,
    _always_scan: bool = False,
) -> Callable[[TrainState, Dict[str, jnp.ndarray]],
              Tuple[TrainState, Dict[str, jnp.ndarray]]]:
    """Build ``(state, batch) -> (state, metrics)`` for any preset.

    Sharding contracts: ``dp`` — state replicated (int8_ef's
    ``comm_residual`` sharded ``P('data')``), batch ``P('data')``,
    shard_map; ``sp`` — state replicated, batch ``P('data', 'seq')``,
    shard_map (vit_sod only; ``sp_strategy`` picks ring vs ulysses);
    ``tp``/``fsdp`` — GSPMD jit with ``state_shardings`` (required;
    from ``rules.shard_state_by_rules`` — the Megatron tables for tp,
    the empty-table + ``fsdp_fallback_rule`` layout for fsdp),
    collectives inserted by the partitioner.  ``steps_per_dispatch=k >
    1`` scans k steps per dispatch over a new leading stacked axis
    (``chunked_step_fn``) — the ONE chunking seam all presets share.
    ``data_hosts>1`` routes each dp bucket through the two-level
    ICI x DCN reduction (``mesh.hier_data_groups``).
    """
    if preset not in PRESETS:
        raise ValueError(f"preset must be one of {PRESETS}, got {preset!r}")
    gspmd = preset in ("tp", "fsdp")
    if gspmd and state_shardings is None:
        raise ValueError(
            f"the {preset} (GSPMD) preset needs state_shardings — build "
            "them with rules.shard_state_by_rules(state, mesh, "
            "zero=..., fallback=...)")
    if preset != "dp" and grad_compression != "none":
        raise ValueError(
            "grad_compression applies to the dp preset's bucketed "
            f"reducer only (preset={preset!r}: the GSPMD partitioner / "
            "SP reduction schedule their own collectives)")
    if preset != "dp" and data_hosts > 1:
        raise ValueError(
            "mesh.data_hosts>1 (the two-level ICI x DCN reduction) "
            f"applies to the dp preset's bucketed reducer only, got "
            f"preset={preset!r}")
    if preset == "sp":
        from .sp import validate_sp_strategy

        if getattr(loss_cfg, "fused_kernel", False):
            import logging

            logging.getLogger(__name__).warning(
                "loss.fused_kernel is a no-op on the sequence-parallel "
                "path: the SP loss already psums sufficient statistics "
                "inline (docs/PERFORMANCE.md)")
        validate_sp_strategy(model, mesh, sp_strategy)
    resolve_remat_policy(remat_policy)  # fail fast on typos, remat or not
    lkw = _loss_kwargs(loss_cfg)
    seq = mesh.shape.get("seq", 1)
    bucket_bytes = int(comm_bucket_mb * 2 ** 20)
    hierarchy = hier_data_groups(mesh, data_hosts)
    ef = grad_compression == "int8_ef"
    # ZeRO-2: the gradient tree is pinned to the buffer layout so the
    # partitioner reduce-scatters instead of materializing the full
    # replicated tree between reduce and update.
    grad_constraint = None
    if gspmd and zero >= 2 and state_shardings is not None:
        grad_constraint = jax.tree_util.tree_map(
            lambda s: s, state_shardings.params)

    def _rng(step):
        # Per-preset RNG folds — each reproduced EXACTLY from its
        # legacy builder so dropout draws replay bit-identically.
        base = jax.random.fold_in(jax.random.PRNGKey(0), step)
        if preset == "dp":
            return jax.random.fold_in(base, lax.axis_index("data"))
        if preset == "sp":
            return jax.random.fold_in(
                base,
                lax.axis_index("data") * seq + lax.axis_index("seq"))
        return base  # tp/GSPMD: global semantics, no named axis

    def _forward_loss(state, batch, rng):
        """(grads, comps, new_stats) for the preset's forward+loss."""
        if preset == "sp":
            from .sp import _sp_apply, _sp_hybrid_loss, _sp_ssim_loss

            image, mask = batch["image"], batch["mask"]

            def apply_fn(params, image):
                return _sp_apply(model, {"params": params}, image,
                                 train=True, rngs={"dropout": rng},
                                 sp_strategy=sp_strategy)

            apply_fn = maybe_remat(apply_fn, remat, remat_policy)

            def loss_fn(params):
                outs = apply_fn(params, image)
                if not loss_cfg.deep_supervision:
                    outs = outs[:1]  # primary head only
                total = jnp.float32(0.0)
                comps: Dict[str, jnp.ndarray] = {}
                for level in outs:
                    t, c = _sp_hybrid_loss(
                        level, mask, bce_w=loss_cfg.bce,
                        iou_w=loss_cfg.iou, cel_w=loss_cfg.cel)
                    if getattr(loss_cfg, "ssim", 0.0):
                        c["ssim"] = _sp_ssim_loss(
                            level, mask,
                            window_size=getattr(loss_cfg, "ssim_window",
                                                11))
                        t = t + loss_cfg.ssim * c["ssim"]
                    total = total + t
                    for k, v in c.items():
                        if k != "total":
                            comps[k] = comps.get(k, jnp.float32(0.0)) + v
                comps["total"] = total
                return total, comps

            grads, comps = jax.grad(loss_fn, has_aux=True)(state.params)
            return grads, comps, state.batch_stats

        def apply_fn(params, batch_stats, image, depth):
            return model.apply(
                {"params": params, "batch_stats": batch_stats},
                image, depth, train=True,
                mutable=["batch_stats"], rngs={"dropout": rng})

        apply_fn = maybe_remat(apply_fn, remat, remat_policy)

        def loss_fn(params):
            outs, mut = apply_fn(params, state.batch_stats,
                                 batch["image"], batch.get("depth"))
            if not loss_cfg.deep_supervision:
                outs = outs[:1]  # primary head only, uniform across steps
            total, comps = deep_supervision_loss(outs, batch["mask"],
                                                 **lkw)
            return total, (comps, mut.get("batch_stats",
                                          state.batch_stats))

        grads, (comps, new_stats) = jax.grad(loss_fn, has_aux=True)(
            state.params)
        return grads, comps, new_stats

    def _reduce(grads, comps, residual=None):
        """Per-preset gradient/metric reduction — the comm seam.
        Returns ``(grads, comps, new_residual)``; the residual is only
        live on the dp int8_ef arm."""
        if preset == "dp":
            if bucket_bytes > 0 or hierarchy is not None or ef:
                if ef:
                    grads, residual = rules_mod.bucketed_pmean(
                        grads, "data", bucket_bytes,
                        compression=grad_compression,
                        hierarchy=hierarchy, residual=residual)
                else:
                    grads = rules_mod.bucketed_pmean(
                        grads, "data", bucket_bytes,
                        compression=grad_compression,
                        hierarchy=hierarchy)
            else:
                grads = lax.pmean(grads, "data")
            comps = lax.pmean(comps, "data")
        elif preset == "sp":
            # SUM over seq recovered by pmean (see parallel/sp.py);
            # data is the usual DP mean.  comps are already seq-global.
            grads = lax.pmean(grads, ("data", "seq"))
            comps = lax.pmean(comps, "data")
        elif grad_constraint is not None:
            grads = lax.with_sharding_constraint(grads, grad_constraint)
        return grads, comps, residual

    def _finish(state, grads, comps, new_stats):
        """Optimizer/EMA/metric tail — identical on every preset."""
        new_state = apply_update(state, grads, new_stats, tx,
                                 ema_decay=ema_decay)
        metrics = dict(comps)
        metrics["grad_norm"] = optax.global_norm(grads)
        maybe_health_metrics(metrics, state.params, grads,
                             new_state.params, health)
        nfc = notfinite_count(new_state.opt_state)
        if nfc is not None:
            metrics["notfinite_count"] = jnp.asarray(nfc, jnp.float32)
        if schedule is not None:
            metrics["lr"] = jnp.asarray(schedule(state.step), jnp.float32)
        return new_state, metrics

    def step_fn(state: TrainState, batch):
        if preset != "sp":
            batch = rescale_batch(batch, scale_hw)
        rng = _rng(state.step)
        grads, comps, new_stats = _forward_loss(state, batch, rng)
        grads, comps, _ = _reduce(grads, comps)
        return _finish(state, grads, comps, new_stats)

    def step_fn_ef(carry, batch):
        # int8_ef: the carry is (state-without-residual, residual); the
        # residual's local block is (1, n_elems) — its replica row.
        state, residual = carry
        batch = rescale_batch(batch, scale_hw)
        rng = _rng(state.step)
        grads, comps, new_stats = _forward_loss(state, batch, rng)
        grads, comps, new_res = _reduce(grads, comps, residual[0])
        new_state, metrics = _finish(state, grads, comps, new_stats)
        return (new_state, new_res[None]), metrics

    inner_fn = step_fn_ef if ef else step_fn
    body = chunked_step_fn(inner_fn, steps_per_dispatch,
                           always_scan=_always_scan)
    donated = (0,) if donate else ()
    if donate_batch:  # fit feeds each prefetched batch exactly once
        donated = donated + (1,)
    if gspmd:
        batch_in = (batch_sharding(mesh) if body is inner_fn
                    else NamedSharding(mesh, chunk_batch_spec(batch_spec())))
        replicated = NamedSharding(mesh, P())
        return jax.jit(
            body,
            in_shardings=(state_shardings, batch_in),
            out_shardings=(state_shardings, replicated),
            donate_argnums=donated,
        )
    base = P("data") if preset == "dp" else P("data", "seq")
    batch_in = base if body is inner_fn else chunk_batch_spec(base)
    if ef:
        sharded = shard_map(
            body,
            mesh=mesh,
            in_specs=((P(), P("data")), batch_in),
            out_specs=((P(), P("data")), P()),
            check_vma=False,
        )
        inner = jax.jit(sharded, donate_argnums=donated)

        def step(state: TrainState, batch):
            # The public contract stays (state, batch) -> (state,
            # metrics): split the residual out of the state for the
            # carry tuple and reattach it after.
            core = state.replace(comm_residual=None)
            (core, res), metrics = inner((core, state.comm_residual),
                                         batch)
            return core.replace(comm_residual=res), metrics

        # .lower keeps the AOT consumers working (capacity record_jit,
        # tools/dump_hlo.py) — same split, handed to the jit's lower.
        step.lower = lambda state, batch: inner.lower(
            (state.replace(comm_residual=None), state.comm_residual),
            batch)
        return step
    sharded = shard_map(
        body,
        mesh=mesh,
        in_specs=(P(), batch_in),
        out_specs=(P(), P()),
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=donated)


# -- comm/ZeRO accounting (feeds the PR 11 capacity ledger) -----------

def comm_plan(state, mesh: Mesh, *, preset: str, zero: int = 0,
              comm_bucket_mb: float = 0.0,
              grad_compression: str = "none",
              data_hosts: int = 1) -> Dict[str, Any]:
    """Price the step's gradient collectives + ZeRO HBM saving from
    shapes alone (no tracing): per-collective payload bytes, axis size
    and link level (``ici``/``dcn``), the bucket count, a structural
    overlap estimate, and the per-device optimizer/EMA bytes ZeRO
    removes.  The capacity ledger (``CapacityLedger.record_comm``)
    turns this into the ``dsod_capacity_comm_*`` families (DCN legs
    into the ``_dcn_*`` families); tools/roofline.py prices the same
    plan offline against ICI and DCN bandwidth.

    ``data_hosts>1`` expands each dp bucket into its three hierarchical
    legs: intra-host reduce-scatter (ici, full payload), inter-host
    all-reduce (dcn, payload/chips_per_host — the whole point), intra-
    host all-gather (ici).  int8_ef prices the achievable 1 B/elem wire
    (0.25 x f32) — XLA transports int32, so this is the contract for
    a wire-level int8 transport, stated in docs/PERFORMANCE.md.

    Overlap estimate is STRUCTURAL, not measured: with backward-ordered
    buckets every bucket except the final one (the earliest layers,
    reduced last) can overlap remaining backward compute, so
    ``overlap_frac = 1 - last_bucket_bytes / total``; a monolithic
    reduce (or the GSPMD presets, whose schedule the partitioner owns)
    reports 0.  The measured number stays a TPU-window item
    (tools/tpu_agenda_r18.sh).
    """
    leaves = jax.tree_util.tree_leaves(state.params)
    shapes = [(g.shape, g.dtype) for g in leaves]
    sizes = [int(np.prod(s or (1,))) * np.dtype(d).itemsize
             for s, d in shapes]
    wire_scale = {"bf16": 0.5, "int8_ef": 0.25}.get(grad_compression,
                                                    1.0)
    n_data = mesh.shape.get("data", 1)
    collectives = []
    if preset == "dp":
        bucket_bytes = int(comm_bucket_mb * 2 ** 20)
        buckets = rules_mod.grad_buckets(shapes, bucket_bytes)
        chips = n_data // data_hosts if data_hosts > 1 else n_data
        for i, bucket in enumerate(buckets):
            payload = int(sum(sizes[j] for j in bucket) * wire_scale)
            stem = (f"grad_bucket_{i:02d}" if len(buckets) > 1
                    else "grad_allreduce")
            if data_hosts > 1:
                collectives.extend([
                    {"name": f"{stem}_rs", "kind": "reduce_scatter",
                     "axis": "data", "axis_size": chips, "level": "ici",
                     "bytes": payload},
                    {"name": f"{stem}_ar", "kind": "psum",
                     "axis": "data", "axis_size": data_hosts,
                     "level": "dcn", "bytes": payload // chips},
                    {"name": f"{stem}_ag", "kind": "all_gather",
                     "axis": "data", "axis_size": chips, "level": "ici",
                     "bytes": payload},
                ])
            else:
                collectives.append({
                    "name": stem, "kind": "psum", "axis": "data",
                    "axis_size": n_data, "level": "ici",
                    "bytes": payload})
        last = sum(sizes[j] for j in buckets[-1]) if buckets else 0
        overlap = (1.0 - last / max(sum(sizes), 1)
                   if len(buckets) > 1 else 0.0)
    elif preset == "fsdp":
        # The partitioner all-gathers the sharded params just-in-time
        # in forward AND backward, and reduce-scatters grads into the
        # 1/N updates — the textbook FSDP schedule, priced at the param
        # payload per leg.
        payload = sum(sizes)
        for name, kind in (("param_allgather_fwd", "all_gather"),
                           ("param_allgather_bwd", "all_gather"),
                           ("grad_reduce_scatter", "reduce_scatter")):
            collectives.append({
                "name": name, "kind": kind, "axis": "data",
                "axis_size": n_data, "level": "ici",
                "bytes": payload})
        overlap = 0.0
    elif preset == "sp":
        n = n_data * mesh.shape.get("seq", 1)
        collectives.append({
            "name": "grad_allreduce", "kind": "psum",
            "axis": "data,seq", "axis_size": n,
            "bytes": sum(sizes)})
        overlap = 0.0
    else:  # tp/GSPMD: the partitioner owns the schedule; with ZeRO the
        # reduce becomes reduce-scatter + update + param all-gather.
        kind = "reduce_scatter+all_gather" if zero else "all_reduce"
        collectives.append({
            "name": "grad_allreduce", "kind": kind, "axis": "data",
            "axis_size": n_data, "bytes": sum(sizes)})
        overlap = 0.0
    saved = 0
    if preset == "fsdp":
        # FSDP sharding saves params + optimizer buffers + EMA: the
        # whole state except batch_stats shards over data.
        fallback = rules_mod.fsdp_fallback_rule(mesh)
        specs = rules_mod.state_specs(
            state, mesh, rules=rules_mod.PRESET_PARAM_RULES["fsdp"],
            zero=zero, fallback=fallback)
        for tree, spec in ((state.params, specs.params),
                           (state.opt_state, specs.opt_state),
                           (state.ema_params, specs.ema_params)):
            if tree is None:
                continue
            saved += (rules_mod.tree_bytes(tree)
                      - rules_mod.sharded_tree_bytes(tree, spec, mesh))
    elif zero and preset == "tp":
        specs = rules_mod.state_specs(state, mesh, zero=zero)
        for tree, spec in ((state.opt_state, specs.opt_state),
                           (state.ema_params, specs.ema_params)):
            if tree is None:
                continue
            saved += (rules_mod.tree_bytes(tree)
                      - rules_mod.sharded_tree_bytes(tree, spec, mesh))
    stems = {c["name"].rsplit("_rs", 1)[0].rsplit("_ar", 1)[0]
             .rsplit("_ag", 1)[0] for c in collectives
             if c["name"].startswith("grad_bucket")}
    return {
        "collectives": collectives,
        "n_buckets": len(stems) or 1,
        "overlap_frac": round(overlap, 6),
        "zero_hbm_saved_bytes": int(saved),
    }


def seed_comm_residual(state, mesh: Mesh) -> TrainState:
    """Seed the int8_ef error-feedback residual: a zero
    ``(n_data, n_grad_elems)`` f32 array sharded ``P('data')`` — row r
    is replica r's accumulated quantization error.  A state that
    already carries a residual (e.g. restored from a checkpoint) keeps
    its values; it is only (re)placed onto the mesh."""
    sharding = NamedSharding(mesh, P("data"))
    existing = getattr(state, "comm_residual", None)
    if existing is not None:
        return state.replace(
            comm_residual=jax.device_put(jnp.asarray(existing),
                                         sharding))
    shapes = [(g.shape, g.dtype)
              for g in jax.tree_util.tree_leaves(state.params)]
    n = rules_mod.comm_residual_size(shapes, 0)
    n_data = mesh.shape.get("data", 1)
    return state.replace(
        comm_residual=jax.device_put(jnp.zeros((n_data, n), jnp.float32),
                                     sharding))


def prepare_train_step(cfg, model, tx, mesh: Mesh, schedule, state, *,
                       steps_per_dispatch: int = 1,
                       scale_hw: Optional[Tuple[int, int]] = None,
                       donate: bool = True, donate_batch: bool = False):
    """One-call routing for bench.py / tools/dump_hlo.py: select the
    preset, place the state (replicated, or rule/ZeRO-sharded for the
    GSPMD presets — Megatron tables for tp, empty table +
    ``fsdp_fallback_rule`` for fsdp), seed the int8_ef residual when
    asked for, and build the unified step.  Returns ``(state, step,
    plan)`` where ``plan`` is ``comm_plan``'s dict.  fit() wires the
    presets itself (it owns validation + the multi-scale factory) but
    calls the SAME builder."""
    from ..configs.base import validate_parallel

    validate_parallel(cfg)
    preset = select_preset(cfg, mesh)
    zero = effective_zero(cfg)
    data_hosts = getattr(cfg.mesh, "data_hosts", 1)
    kw = dict(schedule=schedule, donate=donate, remat=cfg.model.remat,
              ema_decay=cfg.optim.ema_decay, scale_hw=scale_hw,
              donate_batch=donate_batch,
              remat_policy=cfg.model.remat_policy,
              steps_per_dispatch=steps_per_dispatch,
              health=cfg.health_numerics,
              comm_bucket_mb=cfg.parallel.comm_bucket_mb,
              grad_compression=cfg.parallel.grad_compression,
              data_hosts=data_hosts, zero=zero)
    if preset == "tp":
        state, shardings = rules_mod.shard_state_by_rules(
            state, mesh, zero=zero)
        kw["state_shardings"] = shardings
    elif preset == "fsdp":
        state, shardings = rules_mod.shard_state_by_rules(
            state, mesh, rules=rules_mod.PRESET_PARAM_RULES["fsdp"],
            zero=zero, fallback=rules_mod.fsdp_fallback_rule(mesh))
        kw["state_shardings"] = shardings
    else:
        # Replicate first, THEN seed the residual — seeding places the
        # residual P('data'), which a blanket replicate would undo.
        residual = getattr(state, "comm_residual", None)
        state = jax.device_put(state.replace(comm_residual=None),
                               replicated_sharding(mesh))
        if cfg.parallel.grad_compression == "int8_ef":
            state = seed_comm_residual(
                state.replace(comm_residual=residual), mesh)
        if preset == "sp":
            kw["sp_strategy"] = cfg.mesh.sp_strategy
    step = make_unified_train_step(model, cfg.loss, tx, mesh,
                                   preset=preset, **kw)
    plan = comm_plan(state, mesh, preset=preset, zero=zero,
                     comm_bucket_mb=cfg.parallel.comm_bucket_mb,
                     grad_compression=cfg.parallel.grad_compression,
                     data_hosts=data_hosts)
    return state, step, plan
