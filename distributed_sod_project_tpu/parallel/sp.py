"""Sequence-parallel training step (SURVEY.md §5 "long-context").

The reference has no sequence axis to scale (fixed 320×320 CNNs); this
is the TPU build's long-context path: ``vit_sod``'s global attention is
quadratic in tokens, so past single-chip memory/FLOPs the token dim
must shard.  Layout (the ``seq`` mesh axis):

- every batch leaf is sharded ``P('data', 'seq')``: batch over
  ``data``, image ROWS over ``seq`` — patch rows map 1:1 to token
  blocks because the model's patchify is halo-free (models/vit_sod.py),
- each device runs the FULL module (patchify → blocks → head) on its
  row slice, with ``parallel.ring_attention`` as the attention core —
  the ppermute ring is the only cross-device traffic in the forward,
- the loss decomposes exactly: BCE pixel sums and the IoU/CEL
  per-image region sums are computed locally and ``psum``-ed over
  ``seq`` BEFORE the ratios, so the objective equals the single-device
  one to numerics (tests assert grad equivalence),
- gradients: every device's autodiff yields its token block's
  contribution, so the true gradient is ``psum`` over ``seq`` and
  ``pmean`` over ``data`` (DP semantics on the batch axis).

SSIM is the one loss term that does NOT decompose over row blocks (its
11×11 windows straddle block edges); configs with ``loss.ssim > 0`` are
rejected rather than silently approximated.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..train.state import TrainState
from ..train.step import apply_update, notfinite_count
from .ring_attention import ring_attention


def sp_batch_sharding(mesh: Mesh) -> NamedSharding:
    """Batch over ``data``, image rows (dim 1) over ``seq``."""
    return NamedSharding(mesh, P("data", "seq"))


def _sp_hybrid_loss(logits, mask, *, bce_w, iou_w, cel_w,
                    iou_eps=1.0, cel_eps=1e-6, axis="seq"):
    """BCE + IoU + CEL over row-sharded logits/mask — exact: sufficient
    statistics psum over the ``seq`` axis before any ratio/mean."""
    x = logits.astype(jnp.float32).reshape(logits.shape[0], -1)
    t = mask.astype(jnp.float32).reshape(mask.shape[0], -1)
    bce_i = jnp.sum(jnp.maximum(x, 0.0) - x * t
                    + jnp.log1p(jnp.exp(-jnp.abs(x))), axis=-1)
    p = jax.nn.sigmoid(x)
    inter_i = jnp.sum(p * t, axis=-1)
    psum_i = jnp.sum(p, axis=-1)
    tsum_i = jnp.sum(t, axis=-1)
    # Global per-image sums: this device's rows + everyone else's.
    bce_i, inter_i, psum_i, tsum_i = lax.psum(
        (bce_i, inter_i, psum_i, tsum_i), axis)
    n_pix_total = x.shape[1] * lax.axis_size(axis)

    comps: Dict[str, jnp.ndarray] = {}
    total = jnp.float32(0.0)
    if bce_w:
        comps["bce"] = bce_i.mean() / n_pix_total
        total += bce_w * comps["bce"]
    if iou_w:
        union = psum_i + tsum_i - inter_i
        comps["iou"] = jnp.mean(
            1.0 - (inter_i + iou_eps) / (union + iou_eps))
        total += iou_w * comps["iou"]
    if cel_w:
        tot = psum_i + tsum_i
        comps["cel"] = jnp.mean((tot - 2.0 * inter_i) / (tot + cel_eps))
        total += cel_w * comps["cel"]
    comps["total"] = total
    return total, comps


def _sp_apply(model, variables, image, *, train: bool, rngs=None):
    """The shared SP forward: derive this device's (row offset, full
    grid) from its ``seq`` position and run the module on its row slice
    with ring attention as the attention core.  Single definition so
    train and eval geometry cannot diverge."""
    local_rows = image.shape[1] // model.patch
    seq = lax.axis_size("seq")
    row_off = lax.axis_index("seq") * local_rows
    full_grid = (local_rows * seq, image.shape[2] // model.patch)
    return model.apply(
        variables, image, None, train=train,
        attn_fn=partial(ring_attention, axis_name="seq"),
        full_grid=full_grid, pos_row_offset=row_off,
        **({"rngs": rngs} if rngs is not None else {}))


def make_sp_eval_step(model, mesh: Mesh) -> Callable:
    """Sequence-parallel forward-only step: ``(variables, batch) ->
    probs`` with image rows sharded over ``seq`` and ring attention
    crossing the blocks — the eval/inference path for resolutions whose
    full-attention scores ([B,h,N,N]) exceed one chip's memory.  Output
    probs come back sharded the same way; a host ``np.asarray`` gathers
    them.  Math is identical to the single-device forward (ring
    attention is exact)."""

    def eval_fn(variables, batch):
        outs = _sp_apply(model, variables, batch["image"], train=False)
        return jax.nn.sigmoid(outs[0][..., 0].astype(jnp.float32))

    sharded = jax.shard_map(
        eval_fn,
        mesh=mesh,
        in_specs=(P(), P("data", "seq")),
        out_specs=P("data", "seq"),
        check_vma=False,
    )
    return jax.jit(sharded)


def make_sp_train_step(
    model,
    loss_cfg,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    schedule: Optional[optax.Schedule] = None,
    donate: bool = True,
    ema_decay: float = 0.0,
    donate_batch: bool = False,
) -> Callable[[TrainState, Dict[str, jnp.ndarray]],
              Tuple[TrainState, Dict[str, jnp.ndarray]]]:
    """Build the sequence-parallel ``(state, batch) -> (state, metrics)``.

    Contract: ``state`` replicated; batch leaves ``P('data', 'seq')``
    (global shapes; each device sees its (batch, rows) tile).  The
    model must be halo-free over rows with an injectable attention
    core (``vit_sod``).
    """
    if getattr(loss_cfg, "ssim", 0.0):
        raise ValueError(
            "loss.ssim does not decompose over the seq axis (11x11 "
            "windows straddle row-block edges) — set loss.ssim=0 for "
            "sequence-parallel training")
    seq = mesh.shape["seq"]

    def step_fn(state: TrainState, batch):
        rng = jax.random.fold_in(
            jax.random.fold_in(jax.random.PRNGKey(0), state.step),
            lax.axis_index("data") * seq + lax.axis_index("seq"))
        image, mask = batch["image"], batch["mask"]

        def loss_fn(params):
            outs = _sp_apply(model, {"params": params}, image,
                             train=True, rngs={"dropout": rng})
            if not loss_cfg.deep_supervision:
                outs = outs[:1]  # primary head only, uniform across steps
            # DP convention (losses/deep_supervision.py): SUM over
            # levels, per-term components summed for logging.
            total = jnp.float32(0.0)
            comps: Dict[str, jnp.ndarray] = {}
            for level in outs:
                t, c = _sp_hybrid_loss(
                    level, mask, bce_w=loss_cfg.bce, iou_w=loss_cfg.iou,
                    cel_w=loss_cfg.cel)
                total = total + t
                for k, v in c.items():
                    if k != "total":
                        comps[k] = comps.get(k, jnp.float32(0.0)) + v
            comps["total"] = total
            return total, comps

        grads, comps = jax.grad(loss_fn, has_aux=True)(state.params)
        # The true grad is the SUM of per-token-block contributions
        # over ``seq`` — but under shard_map the loss's psum'd
        # statistics transpose back as psum (no replication tracking,
        # check_vma=False), so each device's autodiff already carries
        # an extra ``seq`` factor on its block contribution.  pmean
        # over ``seq`` therefore recovers exactly that sum; ``data`` is
        # the usual DP mean.  Grad equivalence vs a single-device step
        # is asserted to numerics in tests/test_vit_sod.py.
        grads = lax.pmean(grads, ("data", "seq"))
        comps = lax.pmean(comps, "data")  # already seq-global

        new_state = apply_update(state, grads, state.batch_stats, tx,
                                 ema_decay=ema_decay)
        metrics = dict(comps)
        metrics["grad_norm"] = optax.global_norm(grads)
        nfc = notfinite_count(new_state.opt_state)
        if nfc is not None:
            metrics["notfinite_count"] = jnp.asarray(nfc, jnp.float32)
        if schedule is not None:
            metrics["lr"] = jnp.asarray(schedule(state.step), jnp.float32)
        return new_state, metrics

    sharded = jax.shard_map(
        step_fn,
        mesh=mesh,
        in_specs=(P(), P("data", "seq")),
        out_specs=(P(), P()),
        check_vma=False,
    )
    donated = (0,) if donate else ()
    if donate_batch:
        donated = donated + (1,)
    return jax.jit(sharded, donate_argnums=donated)
