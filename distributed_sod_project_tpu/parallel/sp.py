"""Sequence-parallel building blocks (SURVEY.md §5 "long-context").

The SP train step itself is built by the rules engine
(parallel/engine.py, ``preset="sp"``) from the loss/apply/eval pieces
defined here.

The reference has no sequence axis to scale (fixed 320×320 CNNs); this
is the TPU build's long-context path: ``vit_sod``'s global attention is
quadratic in tokens, so past single-chip memory/FLOPs the token dim
must shard.  Layout (the ``seq`` mesh axis):

- every batch leaf is sharded ``P('data', 'seq')``: batch over
  ``data``, image ROWS over ``seq`` — patch rows map 1:1 to token
  blocks because the model's patchify is halo-free (models/vit_sod.py),
- each device runs the FULL module (patchify → blocks → head) on its
  row slice, with ``parallel.ring_attention`` as the attention core —
  the ppermute ring is the only cross-device traffic in the forward,
- the loss decomposes exactly: BCE pixel sums and the IoU/CEL
  per-image region sums are computed locally and ``psum``-ed over
  ``seq`` BEFORE the ratios, so the objective equals the single-device
  one to numerics (tests assert grad equivalence),
- gradients: every device's autodiff yields its token block's
  contribution, so the true gradient is ``psum`` over ``seq`` and
  ``pmean`` over ``data`` (DP semantics on the batch axis).

SSIM does not decompose pointwise over row blocks (its 11×11 windows
straddle block edges), but it is exactly computable with a 5-row halo
exchange: each device ppermutes its boundary rows of the five windowed
moment maps to its ``seq`` neighbors, blurs the extended block, and
keeps only the window outputs centred on its own rows.  ``ppermute``
leaves zeros where no neighbor exists, which is exactly the SAME
zero-padding the single-device blur applies at global image edges — so
the full BASNet hybrid loss (BCE+IoU+SSIM, [B:5]) trains under SP to
numerics (grad-equivalence asserted in tests/test_vit_sod.py).
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Dict

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..losses.ssim import _C1, _C2, _blur, gaussian_window
from .ring_attention import ring_attention
from ..utils.compat import axis_size, shard_map


def sp_batch_sharding(mesh: Mesh) -> NamedSharding:
    """Batch over ``data``, image rows (dim 1) over ``seq``."""
    return NamedSharding(mesh, P("data", "seq"))


def _sp_hybrid_loss(logits, mask, *, bce_w, iou_w, cel_w,
                    iou_eps=1.0, cel_eps=1e-6, axis="seq"):
    """BCE + IoU + CEL over row-sharded logits/mask — exact: sufficient
    statistics psum over the ``seq`` axis before any ratio/mean."""
    x = logits.astype(jnp.float32).reshape(logits.shape[0], -1)
    t = mask.astype(jnp.float32).reshape(mask.shape[0], -1)
    bce_i = jnp.sum(jnp.maximum(x, 0.0) - x * t
                    + jnp.log1p(jnp.exp(-jnp.abs(x))), axis=-1)
    p = jax.nn.sigmoid(x)
    inter_i = jnp.sum(p * t, axis=-1)
    psum_i = jnp.sum(p, axis=-1)
    tsum_i = jnp.sum(t, axis=-1)
    # Global per-image sums: this device's rows + everyone else's.
    bce_i, inter_i, psum_i, tsum_i = lax.psum(
        (bce_i, inter_i, psum_i, tsum_i), axis)
    n_pix_total = x.shape[1] * axis_size(axis)

    comps: Dict[str, jnp.ndarray] = {}
    total = jnp.float32(0.0)
    if bce_w:
        comps["bce"] = bce_i.mean() / n_pix_total
        total += bce_w * comps["bce"]
    if iou_w:
        union = psum_i + tsum_i - inter_i
        comps["iou"] = jnp.mean(
            1.0 - (inter_i + iou_eps) / (union + iou_eps))
        total += iou_w * comps["iou"]
    if cel_w:
        tot = psum_i + tsum_i
        comps["cel"] = jnp.mean((tot - 2.0 * inter_i) / (tot + cel_eps))
        total += cel_w * comps["cel"]
    comps["total"] = total
    return total, comps


def _exchange_row_halo(x, halo: int, axis: str):
    """Attach ``halo`` rows from each ``seq`` neighbor to a row-sharded
    NHWC block: ``[prev's bottom rows, x, next's top rows]``.  Devices
    with no neighbor on a side receive ppermute's zero fill — identical
    to the SAME zero padding the single-device blur sees at the global
    image edge, so no special-casing of edge devices is needed."""
    n = axis_size(axis)
    top = lax.ppermute(x[:, -halo:], axis,
                       [(i, i + 1) for i in range(n - 1)])
    bot = lax.ppermute(x[:, :halo], axis,
                       [(i + 1, i) for i in range(n - 1)])
    return jnp.concatenate([top, x, bot], axis=1)


def _sp_ssim_loss(logits, mask, *, axis="seq", window_size=11, sigma=1.5):
    """Exact ``1 − SSIM`` over row-sharded maps (losses/ssim.py math).

    The five windowed moments (a, b, a², b², ab) are formed locally —
    products of rows live wholly on the row's owner — so ONE halo
    exchange of the stacked moment maps feeds the blur; outputs centred
    on halo rows are sliced away (they belong to the neighbor), and the
    map mean is a psum of local sums over the global pixel count.
    """
    halo = window_size // 2
    if logits.shape[1] < halo:
        raise ValueError(
            f"sequence-parallel SSIM needs >= {halo} image rows per "
            f"device (window {window_size}), got {logits.shape[1]} — "
            "use fewer seq shards or a larger image")
    a = jax.nn.sigmoid(logits.astype(jnp.float32))
    b = mask.astype(jnp.float32)
    c = a.shape[-1]
    stack = jnp.concatenate([a, b, a * a, b * b, a * b], axis=-1)
    ext = _exchange_row_halo(stack, halo, axis)
    blurred = _blur(ext, gaussian_window(window_size, sigma))
    blurred = blurred[:, halo:-halo]  # windows centred on OUR rows
    mu_a, mu_b, e_aa, e_bb, e_ab = (
        blurred[..., i * c:(i + 1) * c] for i in range(5))
    mu_aa, mu_bb, mu_ab = mu_a * mu_a, mu_b * mu_b, mu_a * mu_b
    num = (2.0 * mu_ab + _C1) * (2.0 * (e_ab - mu_ab) + _C2)
    den = (mu_aa + mu_bb + _C1) * ((e_aa - mu_aa) + (e_bb - mu_bb) + _C2)
    local_sum = jnp.sum(num / den)
    global_sum = lax.psum(local_sum, axis)
    n_global = (num.size) * axis_size(axis)  # uniform row blocks
    return 1.0 - global_sum / n_global


def _sp_apply(model, variables, image, *, train: bool, rngs=None,
              sp_strategy: str = "ring"):
    """The shared SP forward: derive this device's (row offset, full
    grid) from its ``seq`` position and run the module on its row slice
    with a sequence-parallel attention core.  Single definition so
    train and eval geometry cannot diverge.

    ``sp_strategy`` picks the core: 'ring' (K/V blocks on a ppermute
    ring) or 'ulysses' (two all-to-alls redistribute heads, full
    sequence per device — needs heads % seq == 0).  Either composes
    with ``model.attn_impl``: 'flash' runs the Pallas kernel inside
    the strategy (per visiting block for the ring, on the full
    sequence for ulysses), 'xla' keeps materialized scores.
    """
    if sp_strategy == "ring":
        core = ring_attention
    elif sp_strategy == "ulysses":
        from .ulysses import ulysses_attention

        core = ulysses_attention
    else:
        raise ValueError(f"mesh.sp_strategy must be 'ring' or "
                         f"'ulysses', got {sp_strategy!r}")
    local_rows = image.shape[1] // model.patch
    seq = axis_size("seq")
    row_off = lax.axis_index("seq") * local_rows
    full_grid = (local_rows * seq, image.shape[2] // model.patch)
    return model.apply(
        variables, image, None, train=train,
        attn_fn=partial(core, axis_name="seq",
                        attn_impl=getattr(model, "attn_impl", "xla")),
        full_grid=full_grid, pos_row_offset=row_off,
        **({"rngs": rngs} if rngs is not None else {}))


def validate_sp_strategy(model, mesh: Mesh, sp_strategy: str) -> None:
    """Build-time geometry check shared by every SP entry point (train
    step, eval step — so test.py gets the friendly error too, not a
    mid-trace shard_map failure).  The runtime check inside
    ``ulysses_attention`` stays as the backstop for direct callers."""
    if sp_strategy == "ulysses":
        seq = mesh.shape.get("seq", 1)
        heads = getattr(model, "heads", 0)
        if heads % seq:
            raise ValueError(
                f"mesh.sp_strategy=ulysses needs heads % seq == 0, got "
                f"heads={heads} seq={seq} — use sp_strategy=ring for "
                "this head count")


def make_sp_eval_step(model, mesh: Mesh,
                      sp_strategy: str = "ring") -> Callable:
    """Sequence-parallel forward-only step: ``(variables, batch) ->
    probs`` with image rows sharded over ``seq`` and the SP attention
    core crossing the blocks — the eval/inference path for resolutions
    whose full-attention scores ([B,h,N,N]) exceed one chip's memory.
    Output probs come back sharded the same way; a host ``np.asarray``
    gathers them.  Math is identical to the single-device forward
    (both strategies are exact)."""
    validate_sp_strategy(model, mesh, sp_strategy)

    def eval_fn(variables, batch):
        outs = _sp_apply(model, variables, batch["image"], train=False,
                         sp_strategy=sp_strategy)
        return jax.nn.sigmoid(outs[0][..., 0].astype(jnp.float32))

    sharded = shard_map(
        eval_fn,
        mesh=mesh,
        in_specs=(P(), P("data", "seq")),
        out_specs=P("data", "seq"),
        check_vma=False,
    )
    return jax.jit(sharded)


def wants_sp_eval(model, mesh) -> bool:
    """Should eval route through the sequence-parallel forward?  True
    on a seq-sharded mesh when the model is SP-capable (halo-free
    patchify with an injectable attention core — ``vit_sod``'s
    ``patch`` attribute is the capability marker).  Single predicate
    shared by test.py's evaluate() and fit()'s inline eval so the two
    can never route the same model differently."""
    return (mesh is not None and mesh.shape.get("seq", 1) > 1
            and hasattr(model, "patch"))


def sp_eval_batch_size(mesh: Mesh, batch_size: int) -> int:
    """Round an eval batch to the ``data``-axis divisor (rows shard
    over ``seq``, so only ``data`` constrains the batch dim)."""
    div = mesh.shape.get("data", 1)
    return max(1, batch_size // div) * div


def make_sp_eval_forward(model, mesh: Mesh, sp_strategy: str = "ring"):
    """Compile the SP eval step once; returns ``bind(variables) ->
    forward(batch) -> probs`` so callers whose variables change between
    sweeps (the inline train eval) rebind without retracing."""
    sp_forward = make_sp_eval_step(model, mesh, sp_strategy)

    def bind(variables):
        from .mesh import replicated_sharding

        variables = jax.device_put(variables, replicated_sharding(mesh))
        return lambda b: sp_forward(
            variables, jax.device_put(b, sp_batch_sharding(mesh)))

    return bind
