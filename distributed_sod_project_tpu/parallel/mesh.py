"""Device mesh construction + sharding rules.

This module is the TPU-native replacement for the reference's entire
distributed runtime (SURVEY.md §2 C3/C4: ``init_process_group('nccl')``,
``DistributedDataParallel``, ``DistributedSampler``).  There is no
hand-written communication backend: the "backend" is a
``jax.sharding.Mesh`` plus the PartitionSpecs below; XLA emits the
collectives (psum over ICI within a host/pod slice, DCN across hosts)
when the train step is compiled (SURVEY.md §5 "distributed communication
backend").

Axes (SURVEY.md §2.3):

- ``data``  — the load-bearing axis: batch-sharded inputs, replicated
  params, gradient psum.  Parity with the reference's DDP.
- ``model`` — tensor-parallel axis for the Swin attention heads
  (stretch config); size 1 in every DP config.
- ``seq``   — sequence/context-parallel axis (ring attention); size 1
  for the 320×320 CNN zoo.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

MeshAxes: Tuple[str, str, str] = ("data", "model", "seq")


def _resolve_axis_sizes(n_devices: int, data: int, model: int, seq: int):
    sizes = {"data": data, "model": model, "seq": seq}
    wild = [k for k, v in sizes.items() if v == -1]
    if len(wild) > 1:
        raise ValueError(f"at most one mesh axis may be -1, got {sizes}")
    fixed = int(np.prod([v for v in sizes.values() if v != -1]))
    if wild:
        if n_devices % fixed:
            raise ValueError(
                f"{n_devices} devices not divisible by fixed axes {sizes}"
            )
        sizes[wild[0]] = n_devices // fixed
    total = int(np.prod(list(sizes.values())))
    if total > n_devices:
        raise ValueError(
            f"mesh {sizes} wants {total} devices, have {n_devices}"
        )
    # total < n_devices is allowed: a fully pinned config (e.g. the
    # single-device reference config) runs on the first `total` devices.
    return sizes["data"], sizes["model"], sizes["seq"]


def make_mesh(
    mesh_cfg=None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build the (data, model, seq) mesh.

    Axis order puts ``model``/``seq`` innermost so tensor/sequence
    shards land on ICI-adjacent chips and the (large, per-step) DP
    gradient psum rides the remaining links.
    """
    devices = list(devices if devices is not None else jax.devices())
    # Backend is resolved by now — safe point to turn on the persistent
    # compilation cache for accelerator runs (no-op on CPU).
    from ..utils.platform import maybe_enable_compilation_cache

    maybe_enable_compilation_cache()
    data = getattr(mesh_cfg, "data", -1) if mesh_cfg is not None else -1
    model = getattr(mesh_cfg, "model", 1) if mesh_cfg is not None else 1
    seq = getattr(mesh_cfg, "seq", 1) if mesh_cfg is not None else 1
    d, m, s = _resolve_axis_sizes(len(devices), data, model, seq)
    arr = np.asarray(devices[: d * m * s]).reshape(d, m, s)
    return Mesh(arr, MeshAxes)


def hier_data_groups(mesh: Mesh, data_hosts: int):
    """axis_index_groups for the two-level (ICI x DCN) data reduction.

    Factors the ``data`` axis as ``(data_hosts, chips_per_host)`` —
    make_mesh's row-major device order puts consecutive data indices on
    the same host, so host h owns data indices
    ``[h*chips, ..., (h+1)*chips - 1]``.  Returns
    ``(intra_groups, inter_groups)``:

    - ``intra_groups`` — one group per host (its chips): the fast ICI
      legs (reduce-scatter, then the final all-gather).
    - ``inter_groups`` — one group per chip position (its peers across
      hosts): the slow DCN all-reduce, carrying only 1/chips_per_host
      of the bucket bytes after the scatter.

    Returns ``None`` when ``data_hosts <= 1`` (flat single-level psum).
    """
    if data_hosts <= 1:
        return None
    data = int(mesh.shape.get("data", 1))
    if data % data_hosts:
        raise ValueError(
            f"mesh.data_hosts={data_hosts} does not divide the data "
            f"axis (size {data}) — the two-level reduction needs equal "
            "chips_per_host on every host")
    chips = data // data_hosts
    if chips == 1:
        raise ValueError(
            f"mesh.data_hosts={data_hosts} leaves 1 chip per host — "
            "the hierarchical reduction degenerates to the flat psum; "
            "use data_hosts=1")
    intra = [[h * chips + j for j in range(chips)]
             for h in range(data_hosts)]
    inter = [[h * chips + j for h in range(data_hosts)]
             for j in range(chips)]
    return intra, inter


def batch_spec() -> P:
    """Batch dim sharded over ``data``; everything else replicated."""
    return P("data")


def replicated_spec() -> P:
    return P()


def batch_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, batch_spec())


def eval_batch_sharding(mesh: Mesh) -> NamedSharding:
    """Eval-forward batch sharding: the batch dim shards over the
    flattened (data, seq) axes so sequence-parallel meshes share eval
    work across every chip instead of replicating it per seq group.
    The ``model`` axis is left out — TP evals keep it for the weight
    sharding.  Equals ``batch_sharding`` on pure-DP meshes."""
    axes = tuple(a for a in ("data", "seq") if mesh.shape.get(a, 1) > 1)
    return NamedSharding(mesh, P(axes or ("data",)))


def eval_batch_divisor(mesh: Mesh) -> int:
    """Round eval batch sizes to a multiple of this so the eval
    sharding divides evenly."""
    return int(np.prod([mesh.shape.get(a, 1) for a in ("data", "seq")]))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, replicated_spec())


def host_shard() -> Tuple[int, int]:
    """(shard_id, num_shards) for the host data pipeline — the analogue
    of the reference's ``DistributedSampler(rank, world_size)``, except
    sharding is per-*host* (each host feeds all its local devices)."""
    return jax.process_index(), jax.process_count()


def host_axis_blocks(mesh: Mesh):
    """This process's contiguous index block along every mesh axis.

    ``{axis: [ids...]}`` where ids are the positions of this host's
    devices on that axis.  The multi-host data plane is only
    well-defined when each host's devices form an axis-aligned
    contiguous block (the default device order gives exactly that);
    anything else raises rather than silently mis-sharding batches.
    Cached per mesh — the result is a constant, and the per-device
    Python scan must not run per batch in the prefetch worker.
    """
    return _host_axis_blocks_cached(mesh)


@functools.lru_cache(maxsize=16)
def _host_axis_blocks_cached(mesh: Mesh):
    local = {d.id for d in jax.local_devices()}
    dev = mesh.devices  # ndarray shaped by mesh.axis_names
    mask = np.vectorize(lambda d: d.id in local)(dev)
    coords = np.argwhere(mask)
    if not len(coords):
        raise ValueError(
            "this host owns none of the mesh's devices (a pinned mesh "
            "smaller than the pod excludes whole hosts) — every "
            "participating process must contribute devices to the mesh")
    blocks = {}
    for i, name in enumerate(mesh.axis_names):
        ids = sorted({int(c[i]) for c in coords})
        if ids != list(range(ids[0], ids[0] + len(ids))):
            raise ValueError(
                f"host devices are non-contiguous on mesh axis "
                f"{name!r}: {ids} — reorder the mesh so each host is "
                "an axis-aligned block")
        blocks[name] = ids
    if len(coords) != int(np.prod([len(v) for v in blocks.values()])):
        raise ValueError(
            "host devices do not form an axis-aligned block on the "
            f"mesh (got {len(coords)} devices vs block "
            f"{ {k: len(v) for k, v in blocks.items()} }) — per-host "
            "batch sharding is undefined for this layout")
    return blocks


def host_batch_shard(mesh: Mesh) -> Tuple[int, int]:
    """(shard_id, num_shards) for the TRAIN loader, derived from where
    this host sits on the ``data`` axis — NOT from process_index: when
    a non-data axis (``seq``, ``model``) spans processes, several hosts
    share one data block and must load IDENTICAL batches (their devices
    hold different row/weight shards of the same images).  For pure-DP
    meshes this reduces to (process_index, process_count)."""
    blocks = host_axis_blocks(mesh)
    data_ids = blocks.get("data") or [0]
    data_size = mesh.shape.get("data", 1)
    if data_size % len(data_ids) or data_ids[0] % len(data_ids):
        # E.g. a pinned data=6 mesh over 2 hosts of 4: host A would
        # cover ids 0-3 (2/3 of the batch) and host B ids 4-5 — no
        # uniform (shard_id, num_shards) describes that; raise per the
        # module contract instead of mis-sharding.
        raise ValueError(
            f"host data block {data_ids} does not tile the data axis "
            f"(size {data_size}) uniformly — size the mesh so every "
            "host covers an equal, aligned data block")
    return data_ids[0] // len(data_ids), data_size // len(data_ids)


def global_batch_array(batch, mesh: Mesh, spec: Optional[P] = None):
    """Assemble per-host numpy batches into global batch-sharded
    ``jax.Array``s (multi-host: each host contributes its slice via
    ``make_array_from_process_local_data``; single-host this is just a
    sharded device_put).  ``spec`` overrides the default batch-only
    sharding (e.g. ``P('data', 'seq')`` for sequence parallelism).

    The host batch must be this host's DATA block (``host_batch_shard``
    is the loader contract).  When ``spec`` row-shards dim 1 over a
    ``seq`` axis that spans processes, each host hands
    ``make_array_from_process_local_data`` only its row block — the
    local data must exactly cover the host's addressable shards.
    """
    sharding = (NamedSharding(mesh, spec) if spec is not None
                else batch_sharding(mesh))
    sp = spec if spec is not None else batch_spec()
    # Which dim (if any) rows shard over ``seq`` — dim 1 for the plain
    # SP spec P('data', 'seq'), dim 2 for the step-chunked spec
    # P(None, 'data', 'seq') (stacked batches, leading k axis).
    row_slice = None
    seq_dim = next((i for i, names in enumerate(sp) if names == "seq"), None)
    if seq_dim is not None:
        seq_ids = host_axis_blocks(mesh).get("seq") or [0]
        seq_size = mesh.shape.get("seq", 1)
        if len(seq_ids) < seq_size:
            row_slice = (seq_dim, seq_ids[0], len(seq_ids), seq_size)

    def place(x):
        x = np.asarray(x)
        if row_slice is not None:
            dim, first, n, total = row_slice
            if x.shape[dim] % total:
                raise ValueError(
                    f"dim {dim} ({x.shape[dim]}) not divisible by the "
                    f"seq axis ({total})")
            blk = x.shape[dim] // total
            idx = [slice(None)] * x.ndim
            idx[dim] = slice(first * blk, (first + n) * blk)
            x = x[tuple(idx)]
        return jax.make_array_from_process_local_data(sharding, x)

    return jax.tree_util.tree_map(place, batch)
