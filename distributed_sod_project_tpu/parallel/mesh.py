"""Device mesh construction + sharding rules.

This module is the TPU-native replacement for the reference's entire
distributed runtime (SURVEY.md §2 C3/C4: ``init_process_group('nccl')``,
``DistributedDataParallel``, ``DistributedSampler``).  There is no
hand-written communication backend: the "backend" is a
``jax.sharding.Mesh`` plus the PartitionSpecs below; XLA emits the
collectives (psum over ICI within a host/pod slice, DCN across hosts)
when the train step is compiled (SURVEY.md §5 "distributed communication
backend").

Axes (SURVEY.md §2.3):

- ``data``  — the load-bearing axis: batch-sharded inputs, replicated
  params, gradient psum.  Parity with the reference's DDP.
- ``model`` — tensor-parallel axis for the Swin attention heads
  (stretch config); size 1 in every DP config.
- ``seq``   — sequence/context-parallel axis (ring attention); size 1
  for the 320×320 CNN zoo.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

MeshAxes: Tuple[str, str, str] = ("data", "model", "seq")


def _resolve_axis_sizes(n_devices: int, data: int, model: int, seq: int):
    sizes = {"data": data, "model": model, "seq": seq}
    wild = [k for k, v in sizes.items() if v == -1]
    if len(wild) > 1:
        raise ValueError(f"at most one mesh axis may be -1, got {sizes}")
    fixed = int(np.prod([v for v in sizes.values() if v != -1]))
    if wild:
        if n_devices % fixed:
            raise ValueError(
                f"{n_devices} devices not divisible by fixed axes {sizes}"
            )
        sizes[wild[0]] = n_devices // fixed
    total = int(np.prod(list(sizes.values())))
    if total > n_devices:
        raise ValueError(
            f"mesh {sizes} wants {total} devices, have {n_devices}"
        )
    # total < n_devices is allowed: a fully pinned config (e.g. the
    # single-device reference config) runs on the first `total` devices.
    return sizes["data"], sizes["model"], sizes["seq"]


def make_mesh(
    mesh_cfg=None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build the (data, model, seq) mesh.

    Axis order puts ``model``/``seq`` innermost so tensor/sequence
    shards land on ICI-adjacent chips and the (large, per-step) DP
    gradient psum rides the remaining links.
    """
    devices = list(devices if devices is not None else jax.devices())
    # Backend is resolved by now — safe point to turn on the persistent
    # compilation cache for accelerator runs (no-op on CPU).
    from ..utils.platform import maybe_enable_compilation_cache

    maybe_enable_compilation_cache()
    data = getattr(mesh_cfg, "data", -1) if mesh_cfg is not None else -1
    model = getattr(mesh_cfg, "model", 1) if mesh_cfg is not None else 1
    seq = getattr(mesh_cfg, "seq", 1) if mesh_cfg is not None else 1
    d, m, s = _resolve_axis_sizes(len(devices), data, model, seq)
    arr = np.asarray(devices[: d * m * s]).reshape(d, m, s)
    return Mesh(arr, MeshAxes)


def batch_spec() -> P:
    """Batch dim sharded over ``data``; everything else replicated."""
    return P("data")


def replicated_spec() -> P:
    return P()


def batch_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, batch_spec())


def eval_batch_sharding(mesh: Mesh) -> NamedSharding:
    """Eval-forward batch sharding: the batch dim shards over the
    flattened (data, seq) axes so sequence-parallel meshes share eval
    work across every chip instead of replicating it per seq group.
    The ``model`` axis is left out — TP evals keep it for the weight
    sharding.  Equals ``batch_sharding`` on pure-DP meshes."""
    axes = tuple(a for a in ("data", "seq") if mesh.shape.get(a, 1) > 1)
    return NamedSharding(mesh, P(axes or ("data",)))


def eval_batch_divisor(mesh: Mesh) -> int:
    """Round eval batch sizes to a multiple of this so the eval
    sharding divides evenly."""
    return int(np.prod([mesh.shape.get(a, 1) for a in ("data", "seq")]))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, replicated_spec())


def host_shard() -> Tuple[int, int]:
    """(shard_id, num_shards) for the host data pipeline — the analogue
    of the reference's ``DistributedSampler(rank, world_size)``, except
    sharding is per-*host* (each host feeds all its local devices)."""
    return jax.process_index(), jax.process_count()


def global_batch_array(batch, mesh: Mesh, spec: Optional[P] = None):
    """Assemble per-host numpy batches into global batch-sharded
    ``jax.Array``s (multi-host: each host contributes its slice via
    ``make_array_from_process_local_data``; single-host this is just a
    sharded device_put).  ``spec`` overrides the default batch-only
    sharding (e.g. ``P('data', 'seq')`` for sequence parallelism)."""
    sharding = (NamedSharding(mesh, spec) if spec is not None
                else batch_sharding(mesh))
    return jax.tree_util.tree_map(
        lambda x: jax.make_array_from_process_local_data(sharding, np.asarray(x)),
        batch,
    )
