"""Data-path graceful degradation: a bounded skip-budget for corrupt
samples.

One truncated JPEG three hours into an epoch should not kill a
pod-scale run — but UNBOUNDED skipping silently trains on a shrinking
dataset, so the budget is finite and exhaustion re-raises the original
error.  ``GuardedDataset`` wraps any map-style dataset (FolderSOD,
SyntheticSOD, …): a fetch that raises, or returns non-finite pixels, is
replaced by the next index (deterministic substitution — every rank
substitutes identically, so multi-host batch composition stays in
lockstep) and counted.  The count surfaces as the ``data_skipped``
train metric instead of an epoch-killing exception.

Backend coverage: the host loader (``_fetch``) and the grain loader
(``_ShardView.__getitem__``) both fetch through ``dataset[i]``, so
wrapping the dataset covers them sample-exactly.  The tf.data backend
decodes inside the TF graph from raw paths; it degrades via
``ignore_errors()`` + an epoch-end shortfall check against the same
budget (data/tfdata.py).  The native C++ batch decoder already falls
back to the (guarded) PIL path on decode errors.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

import numpy as np

from ..utils.logging import get_logger


class SkipBudgetExhausted(RuntimeError):
    pass


class GuardedDataset:
    """Map-style dataset wrapper with a bounded corrupt-sample budget.

    ``skip_budget`` is the total number of substitutions allowed for
    the lifetime of this wrapper (i.e. the run).  ``max_probe`` bounds
    the substitution chain per fetch so a fully-corrupt directory
    fails fast instead of walking the whole dataset.
    """

    def __init__(self, dataset, skip_budget: int = 0,
                 fault_plan=None, max_probe: int = 4,
                 check_finite: bool = True):
        self._dataset = dataset
        self.skip_budget = int(skip_budget)
        self.max_probe = int(max_probe)
        self.check_finite = check_finite
        self._plan = fault_plan
        self.skipped = 0
        self.skipped_indices: List[int] = []
        self._lock = threading.Lock()

    def __len__(self) -> int:
        return len(self._dataset)

    def __getattr__(self, name):
        # stems/img_paths/mean/std/image_size/load_batch… pass through,
        # so every loader backend accepts the wrapper as-is.
        return getattr(self._dataset, name)

    def _fetch_one(self, index: int) -> Dict[str, np.ndarray]:
        if self._plan is not None:
            self._plan.check_sample(index)
        sample = self._dataset[index]
        if self.check_finite:
            img = sample.get("image") if isinstance(sample, dict) else None
            if img is not None and not np.all(np.isfinite(img)):
                raise ValueError(
                    f"non-finite pixels in sample {index} (corrupt decode)")
        return sample

    def _spend(self, index: int, err: Exception) -> None:
        with self._lock:
            if self.skipped >= self.skip_budget:
                raise SkipBudgetExhausted(
                    f"corrupt-sample skip budget ({self.skip_budget}) "
                    f"exhausted at dataset index {index}: {err}") from err
            self.skipped += 1
            self.skipped_indices.append(int(index))
        get_logger().warning(
            "corrupt sample at index %d (%s) — substituting next index "
            "(%d/%d budget spent)", index, err, self.skipped,
            self.skip_budget)

    def __getitem__(self, index: int) -> Dict[str, np.ndarray]:
        index = int(index)
        n = len(self._dataset)
        err: Optional[Exception] = None
        for probe in range(self.max_probe + 1):
            j = (index + probe) % n
            try:
                return self._fetch_one(j)
            except Exception as e:  # noqa: BLE001 — budget decides
                # Every failed probe is a distinct corrupt sample:
                # each one spends budget (and exhaustion raises here).
                self._spend(j, e)
                err = e
        raise SkipBudgetExhausted(
            f"no readable substitute within {self.max_probe} probes of "
            f"index {index}") from err
