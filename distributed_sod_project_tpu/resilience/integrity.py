"""Checkpoint step-dir integrity: validation, manifests, quarantine.

Orbax commits a step atomically by renaming the tmp dir, but the commit
is multi-part: the rename lands before ``_CHECKPOINT_METADATA`` and the
per-item metadata are finalized.  A process killed in that window (an
async save under SIGKILL/preemption) leaves a step dir that
``ocp.CheckpointManager.latest_step()`` happily reports — and restore
then crashes with "No structure could be identified" (reproduced
against orbax 0.7.0).  The helpers here classify such dirs so the
manager can fall back to the newest *valid* checkpoint instead of
raising, and move the corpse aside for post-mortem rather than
deleting evidence.

Validation is structural + (when present) manifest-based:

- structural: the dir is digit-named, carries ``_CHECKPOINT_METADATA``
  at its root, and has at least one item subdir with ``_METADATA``.
- manifest: ``_integrity.json`` (written by our CheckpointManager after
  a save finalizes) records every file's size; any missing/short file
  fails validation.  Absence of the manifest is NOT a failure — the
  writer may have been killed before ``wait()``.

Stdlib-only on purpose: this module is imported by ckpt/manager.py and
must never pull jax/orbax (or anything that could cycle back into the
training stack).
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, Optional, Tuple

MANIFEST_NAME = "_integrity.json"
QUARANTINE_DIRNAME = "_quarantine"

# Files orbax itself mutates after commit (retention metadata) or that
# we write post-hoc; their sizes are allowed to drift from the manifest.
_MANIFEST_EXEMPT = (MANIFEST_NAME,)


def _iter_files(step_dir: str):
    for root, _, files in os.walk(step_dir):
        for fn in files:
            full = os.path.join(root, fn)
            yield os.path.relpath(full, step_dir), full


def write_manifest(step_dir: str) -> Optional[str]:
    """Record every file's size under ``step_dir`` into
    ``_integrity.json`` (atomic write).  Returns the manifest path, or
    None when the dir is missing."""
    if not os.path.isdir(step_dir):
        return None
    files: Dict[str, int] = {}
    for rel, full in _iter_files(step_dir):
        if rel in _MANIFEST_EXEMPT:
            continue
        try:
            files[rel] = os.path.getsize(full)
        except OSError:
            return None  # dir is being mutated under us; don't manifest
    payload = {
        "version": 1,
        "created_unix": time.time(),
        "file_count": len(files),
        "total_bytes": sum(files.values()),
        "files": files,
    }
    path = os.path.join(step_dir, MANIFEST_NAME)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f, indent=0, sort_keys=True)
    os.replace(tmp, path)
    return path


def has_manifest(step_dir: str) -> bool:
    return os.path.isfile(os.path.join(step_dir, MANIFEST_NAME))


def check_manifest(step_dir: str) -> Tuple[bool, str]:
    """Verify manifest-recorded files exist with their recorded sizes.
    A missing manifest passes (see module docstring)."""
    path = os.path.join(step_dir, MANIFEST_NAME)
    if not os.path.isfile(path):
        return True, "no manifest (pre-finalize kill or legacy save)"
    try:
        with open(path) as f:
            manifest = json.load(f)
        files = manifest["files"]
    except (OSError, ValueError, KeyError) as e:
        return False, f"unreadable manifest: {e!r}"
    for rel, size in files.items():
        full = os.path.join(step_dir, rel)
        try:
            actual = os.path.getsize(full)
        except OSError:
            return False, f"manifested file missing: {rel}"
        if actual != int(size):
            return False, (f"size mismatch for {rel}: "
                           f"{actual} != {size} (truncated write)")
    return True, "manifest ok"


def validate_step_dir(step_dir: str) -> Tuple[bool, str]:
    """(ok, reason) for one candidate checkpoint step directory."""
    base = os.path.basename(os.path.normpath(step_dir))
    if not base.isdigit():
        # Orbax tmp dirs ("7.orbax-checkpoint-tmp-123") and anything
        # else non-step-shaped: never a resume candidate.
        return False, f"non-step name {base!r} (tmp/foreign dir)"
    if not os.path.isdir(step_dir):
        return False, "not a directory"
    if not os.path.isfile(os.path.join(step_dir, "_CHECKPOINT_METADATA")):
        return False, ("missing _CHECKPOINT_METADATA — save was killed "
                       "before finalize")
    items = [d for d in sorted(os.listdir(step_dir))
             if os.path.isdir(os.path.join(step_dir, d))]
    if not any(os.path.isfile(os.path.join(step_dir, d, "_METADATA"))
               for d in items):
        return False, "no item dir with _METADATA (partial payload)"
    return check_manifest(step_dir)


def quarantine_step_dir(step_dir: str, reason: str = "") -> Optional[str]:
    """Move a corrupt step dir into ``<root>/_quarantine/`` (evidence
    preserved, step-number scan can never pick it again).  Returns the
    new path, or None if the move failed (cross-host race: another
    process may quarantine first — losing that race is fine)."""
    step_dir = os.path.normpath(step_dir)
    root = os.path.dirname(step_dir)
    base = os.path.basename(step_dir)
    qdir = os.path.join(root, QUARANTINE_DIRNAME)
    os.makedirs(qdir, exist_ok=True)
    dest = os.path.join(qdir, base)
    n = 0
    while os.path.exists(dest):
        n += 1
        dest = os.path.join(qdir, f"{base}.{n}")
    try:
        os.rename(step_dir, dest)
    except OSError:
        return None
    with open(dest + ".reason", "w") as f:
        f.write(reason or "unspecified\n")
    return dest


def list_step_dirs(directory: str) -> Dict[int, str]:
    """All digit-named step dirs under a checkpoint root (no
    validation), as {step: path}."""
    out: Dict[int, str] = {}
    try:
        entries = os.listdir(directory)
    except OSError:
        return out
    for name in entries:
        p = os.path.join(directory, name)
        if name.isdigit() and os.path.isdir(p):
            out[int(name)] = p
    return out


def truncate_step_dir(step_dir: str, *, drop_metadata: bool = True,
                      truncate_bytes: int = 8) -> None:
    """Deterministically corrupt a committed step dir the way a
    preemption mid-finalize does (fault injection / chaos tests):
    remove the commit marker and truncate the largest payload file."""
    meta = os.path.join(step_dir, "_CHECKPOINT_METADATA")
    if drop_metadata and os.path.isfile(meta):
        os.remove(meta)
    # Truncate the biggest file: a partially-flushed shard.
    biggest, size = None, -1
    for rel, full in _iter_files(step_dir):
        if rel in _MANIFEST_EXEMPT:
            continue
        s = os.path.getsize(full)
        if s > size:
            biggest, size = full, s
    if biggest is not None and size > truncate_bytes:
        with open(biggest, "r+b") as f:
            f.truncate(truncate_bytes)
