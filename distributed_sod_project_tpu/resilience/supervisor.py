"""Training supervisor: rollback-and-retry around ``fit``.

TF-Replicator's framing (PAPERS.md): worker failure and restartability
are a property of the training FRAMEWORK, not of ops runbooks.  The
supervisor wraps ``train.loop.fit`` and converts the two recoverable
failure classes this stack actually produces into bounded retries:

- **divergence** — the loop's consecutive-non-finite-updates
  ``RuntimeError`` (train/loop.py, ``optim.skip_nonfinite``).  No bad
  update was applied, so the last checkpoint is sound: roll back and
  re-run.  A transient (one poisoned batch, a bf16 overflow spike)
  succeeds on the plain retry; a persistent divergence gets the
  degradation policy — LR scaled down per retry after the first —
  matching the loop's own advice string ("restart from the last
  checkpoint with a lower lr").
- **restore failure** — a corrupt/truncated checkpoint surfacing as
  orbax/manager errors at resume time.  The quarantine pass moves the
  corpse aside so the next attempt restores the newest *valid* step
  (ckpt/manager.py); veScale's SPMD-consistency argument applies:
  recovery must be provably identical to the uninterrupted run, which
  rollback-to-bitwise-checkpoint + deterministic data order gives us
  (asserted by tests/test_resilience.py).

Everything else (ValueError config errors, OOM, keyboard interrupt)
propagates immediately — retrying non-recoverable errors only burns
the TPU window.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Callable, Dict, Optional

from ..utils.logging import get_logger


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Bounded retry budget + degradation schedule.

    ``degrade_after``: number of retries attempted verbatim before LR
    degradation starts.  The default (1) gives transients one exact
    replay — which keeps the recovered run bitwise-identical to the
    unfaulted one — and only then starts trading reproducibility for
    survival.
    """

    max_retries: int = 3
    degrade_after: int = 1
    lr_factor: float = 0.5
    min_lr_scale: float = 1e-3  # stop degrading below this total scale

    def lr_scale_for(self, attempt: int) -> float:
        """Total LR scale for retry ``attempt`` (1-based)."""
        n = max(0, attempt - self.degrade_after)
        return max(self.min_lr_scale, self.lr_factor ** n)


def is_divergence(err: BaseException) -> bool:
    return (isinstance(err, RuntimeError)
            and "non-finite gradient" in str(err))


def is_restore_failure(err: BaseException) -> bool:
    """Errors the checkpoint path raises for corrupt/unreadable step
    dirs (orbax raises a zoo: FileNotFoundError for missing structure,
    ValueError/KeyError for undecodable payloads)."""
    if isinstance(err, FileNotFoundError):
        return True
    return (isinstance(err, (ValueError, KeyError, OSError))
            and ("checkpoint" in str(err).lower()
                 or "restore" in str(err).lower()))


def is_recoverable(err: BaseException) -> bool:
    return is_divergence(err) or is_restore_failure(err)


def _degraded(cfg, lr_scale: float):
    if lr_scale == 1.0:
        return cfg
    return cfg.replace(
        optim=dataclasses.replace(cfg.optim, lr=cfg.optim.lr * lr_scale))


def run_supervised(
    cfg,
    workdir: Optional[str] = None,
    resume: bool = False,
    max_steps: Optional[int] = None,
    hooks: Optional[Dict[str, Callable]] = None,
    policy: Optional[RetryPolicy] = None,
    fit_fn: Optional[Callable] = None,
) -> Dict[str, float]:
    """Run ``fit`` under rollback-and-retry; returns its final metrics
    plus ``supervisor_retries``/``supervisor_lr_scale``.

    Requires ``cfg.checkpoint_every_steps > 0`` to have anything to
    roll back to (a zero-checkpoint run still gets retry-from-scratch).
    ``fit_fn`` is injectable for tests.
    """
    if fit_fn is None:
        from ..train.loop import fit as fit_fn  # lazy: avoid cycles

    policy = policy or RetryPolicy()
    log = get_logger()
    attempt = 0  # number of retries consumed
    lr_scale = 1.0
    while True:
        try:
            metrics = fit_fn(
                _degraded(cfg, lr_scale),
                workdir=workdir,
                resume=resume or attempt > 0,
                max_steps=max_steps,
                hooks=hooks,
            )
            metrics["supervisor_retries"] = float(attempt)
            metrics["supervisor_lr_scale"] = float(lr_scale)
            return metrics
        except BaseException as err:  # noqa: BLE001 — filtered below
            if not is_recoverable(err):
                raise
            attempt += 1
            if attempt > policy.max_retries:
                log.error(
                    "supervisor: retry budget (%d) exhausted, re-raising",
                    policy.max_retries)
                raise
            # Quarantine anything invalid so the retry's restore lands
            # on the newest VALID checkpoint, then degrade if due.
            ckpt_dir = workdir or cfg.checkpoint_dir
            last_good = _quarantine_and_latest(ckpt_dir)
            lr_scale = policy.lr_scale_for(attempt)
            if getattr(cfg, "flight_recorder", False):
                # The rollback happens BETWEEN fit() attempts (each
                # owns its own recorder), so the supervisor notes it
                # into the same on-disk ring directly — the incident
                # timeline then shows crash → rollback → resume as one
                # sequence.  append_event never raises.
                from ..utils.flightrecorder import append_event

                rec_dir = (getattr(cfg, "recorder_dir", "")
                           or os.path.join(ckpt_dir, "flightrec"))
                append_event(
                    rec_dir, "supervisor_rollback",
                    keep_segments=getattr(cfg, "recorder_keep_segments",
                                          16),
                    attempt=attempt,
                    max_retries=policy.max_retries,
                    failure=("divergence" if is_divergence(err)
                             else "restore_failure"),
                    error=str(err)[:200], rollback_step=last_good,
                    lr_scale=lr_scale)
            log.warning(
                "supervisor: attempt %d/%d after %s: %s — rolling back "
                "to step %s, lr_scale=%g", attempt, policy.max_retries,
                "divergence" if is_divergence(err) else "restore failure",
                err, last_good, lr_scale)


def _quarantine_and_latest(ckpt_dir: str):
    """Move invalid step dirs aside; return the newest valid step (or
    None).  Uses the integrity helpers directly — no orbax manager is
    constructed, so a half-written dir can't wedge the scan."""
    from .integrity import (list_step_dirs, quarantine_step_dir,
                            validate_step_dir)

    latest = None
    for step, path in sorted(list_step_dirs(ckpt_dir).items()):
        ok, reason = validate_step_dir(path)
        if ok:
            latest = step
        else:
            quarantine_step_dir(path, reason)
            get_logger().warning(
                "supervisor: quarantined checkpoint step %d (%s)",
                step, reason)
    return latest
