"""Fault tolerance: checkpoint integrity, supervised retries, watchdog,
data-path degradation, and deterministic fault injection.

The 2026-08-02 TPU window (docs/RESILIENCE.md) showed two failure modes
this package exists for: dispatched programs wedging indefinitely while
``jax.devices()`` still answers, and preemption-truncated orbax step
dirs being selected as the resume point.  Every piece here maps to a
failure already observed or structurally possible in this stack:

- :mod:`.integrity` — validate/quarantine checkpoint step dirs so
  restore always lands on the newest *valid* checkpoint.
- :mod:`.watchdog` — in-process step heartbeat; a wedged step becomes a
  bounded-time exit (code 114) with stack-dump diagnostics.
- :mod:`.supervisor` — wraps ``fit`` with rollback-and-retry on
  divergence/restore failure, with a bounded budget and LR degradation.
- :mod:`.inject` — deterministic, env-gated fault injection points
  driving the chaos suite (tests/test_resilience.py).
- :mod:`.dataguard` — bounded skip-budget for corrupt samples,
  surfaced as a counter metric instead of an epoch-killing exception.
"""

from .dataguard import GuardedDataset
from .inject import FaultPlan, plan_from_env, reset_plans
from .integrity import (quarantine_step_dir, validate_step_dir,
                        write_manifest)
from .watchdog import WATCHDOG_EXIT_CODE, StepWatchdog

__all__ = [
    "GuardedDataset",
    "FaultPlan",
    "plan_from_env",
    "reset_plans",
    "quarantine_step_dir",
    "validate_step_dir",
    "write_manifest",
    "WATCHDOG_EXIT_CODE",
    "StepWatchdog",
    "run_supervised",
]


def run_supervised(*args, **kw):
    """Lazy alias for :func:`.supervisor.run_supervised` (the supervisor
    imports the train loop; importing it eagerly here would cycle
    through ckpt/manager.py's integrity import)."""
    from .supervisor import run_supervised as _run

    return _run(*args, **kw)
