"""In-process step watchdog — the wedged-dispatch detector.

The 2026-08-02 TPU window showed the failure mode this targets: the
runtime keeps answering ``jax.devices()`` while every dispatched
program blocks forever, so the train loop sits inside
``train_step(...)`` indefinitely and nothing ever raises.  No
in-process recovery is possible (the thread is stuck in C++), so the
contract is: detect the stall from a side thread, dump live stack
traces + the last known metrics for post-mortem, and exit the process
with a DISTINCT code (:data:`WATCHDOG_EXIT_CODE`) so the supervising
layer (tools/tpu_watch.sh, a k8s restart policy, or
resilience/supervisor.py run under a process manager) can tell "step
deadline exceeded" from a crash and re-fire cleanly — the next run
``--resume``'s from the last valid checkpoint.

The heartbeat is fed by the train loop's ``StepTimer.tick()`` (one
beat per completed step), so the deadline bounds a SINGLE step, not
the whole run.  The first beat gets a separate, larger grace period:
step 1 includes XLA compilation, which legitimately takes minutes.
"""

from __future__ import annotations

import faulthandler
import os
import sys
import threading
import time
import traceback
from typing import Callable, Dict, Optional

from ..utils.logging import get_logger

# Distinct from Python's 1, SIGKILL's 137, timeout(1)'s 124: a
# supervising shell can case on it.  Documented in docs/RESILIENCE.md.
WATCHDOG_EXIT_CODE = 114


def dump_all_stacks(out=None) -> str:
    """Write every thread's Python stack to ``out`` (default stderr);
    returns the formatted dump.  Uses both the pure-Python formatter
    (readable, thread names) and faulthandler (works even when a
    thread wedges holding odd state)."""
    out = out or sys.stderr
    names = {t.ident: t.name for t in threading.enumerate()}
    parts = []
    for ident, frame in sys._current_frames().items():
        parts.append(f"--- thread {names.get(ident, '?')} ({ident}) ---\n"
                     + "".join(traceback.format_stack(frame)))
    text = "\n".join(parts)
    try:
        out.write(text + "\n")
        faulthandler.dump_traceback(file=out, all_threads=True)
        out.flush()
    except (OSError, ValueError):
        pass  # stderr may be gone during interpreter shutdown
    return text


class StepWatchdog:
    """Heartbeat-deadline monitor running in a daemon thread.

    >>> with StepWatchdog(deadline_s=300) as wd:
    ...     for batch in loader:
    ...         state, m = train_step(state, batch)
    ...         wd.beat(step)          # fed via StepTimer.tick()

    On ``deadline_s`` without a beat the watchdog dumps diagnostics and
    calls ``on_stall`` — by default :func:`os._exit` with
    :data:`WATCHDOG_EXIT_CODE` (``atexit``/orbax finalizers are wedged
    too; a clean shutdown is not on offer).  Tests pass a callable to
    observe the firing in-process.
    """

    def __init__(
        self,
        deadline_s: float,
        *,
        first_deadline_s: Optional[float] = None,
        exit_code: int = WATCHDOG_EXIT_CODE,
        on_stall: Optional[Callable[[str], None]] = None,
        dump_dir: Optional[str] = None,
        poll_s: Optional[float] = None,
    ):
        if deadline_s <= 0:
            raise ValueError(f"deadline_s must be > 0, got {deadline_s}")
        self.deadline_s = float(deadline_s)
        # First beat covers jit compile + data warmup: give it the
        # larger of 3 deadlines or the explicit grace.
        self.first_deadline_s = float(first_deadline_s
                                      if first_deadline_s is not None
                                      else 3.0 * deadline_s)
        self.exit_code = int(exit_code)
        self._on_stall = on_stall
        self.dump_dir = dump_dir
        self._poll_s = float(poll_s) if poll_s else min(
            1.0, self.deadline_s / 4.0)
        self._lock = threading.Lock()
        self._last_beat = None  # None until start()
        self._beats = 0
        self.last_step: Optional[int] = None
        self.last_metrics: Dict[str, float] = {}
        self.fired = False
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ----------------------------------------------------

    def start(self) -> "StepWatchdog":
        if self._thread is not None:
            return self
        self._last_beat = time.monotonic()
        self._thread = threading.Thread(
            target=self._run, name="step-watchdog", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop_evt.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def __enter__(self) -> "StepWatchdog":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False

    # -- heartbeat ----------------------------------------------------

    def beat(self, step: Optional[int] = None,
             metrics: Optional[Dict[str, float]] = None) -> None:
        """One step finished.  Called from the train loop / StepTimer;
        both args are optional diagnostics context."""
        with self._lock:
            self._last_beat = time.monotonic()
            self._beats += 1
            if step is not None:
                self.last_step = int(step)
            if metrics:
                self.last_metrics = dict(metrics)

    def seconds_since_beat(self) -> Optional[float]:
        """Age of the last heartbeat (None before start()) — the
        trainer telemetry sidecar's /healthz reads this so liveness is
        the watchdog's OWN signal, not a second, subtly different
        clock."""
        with self._lock:
            if self._last_beat is None:
                return None
            return time.monotonic() - self._last_beat

    # -- monitor ------------------------------------------------------

    def _run(self) -> None:
        while not self._stop_evt.wait(self._poll_s):
            with self._lock:
                elapsed = time.monotonic() - self._last_beat
                limit = (self.deadline_s if self._beats
                         else self.first_deadline_s)
            if elapsed > limit:
                self._fire(elapsed, limit)
                return

    def _fire(self, elapsed: float, limit: float) -> None:
        self.fired = True
        log = get_logger()
        phase = "step" if self._beats else "first step (incl. compile)"
        msg = (f"WATCHDOG: {phase} exceeded deadline — {elapsed:.1f}s "
               f"since last heartbeat (limit {limit:.1f}s), last step="
               f"{self.last_step}, last metrics={self.last_metrics} — "
               "dumping stacks and exiting with code "
               f"{self.exit_code} (wedged-dispatch mode; resume from "
               "the last valid checkpoint)")
        try:
            log.error(msg)
            sys.stderr.write(msg + "\n")
        except (OSError, ValueError):
            pass
        text = dump_all_stacks()
        if self.dump_dir:
            try:
                os.makedirs(self.dump_dir, exist_ok=True)
                path = os.path.join(
                    self.dump_dir, f"watchdog_stall_{os.getpid()}.txt")
                with open(path, "w") as f:
                    f.write(msg + "\n\n" + text)
                log.error("watchdog stall dump written to %s", path)
            except OSError:
                pass
        if self._on_stall is not None:
            self._on_stall(msg)
            return
        os._exit(self.exit_code)
