"""Deterministic fault injection — the chaos harness's hand on the lever.

Faults are declared in the ``DSOD_FAULTS`` env var (config-free so the
same injection reaches subprocesses and multi-host workers verbatim)
as a comma-separated list of ``kind@where`` specs:

- ``nan_grad@S`` / ``nan_grad@SxN`` — poison one pixel of the batch to
  NaN for the N (default 1) consecutive steps starting at step S
  (1-based, as logged), producing non-finite gradients through the real
  backward path — the bf16-overflow / corrupt-decode divergence mode.
- ``sigterm@S`` — deliver SIGTERM to this process after step S
  completes (preemption arriving mid-epoch).
- ``stall@S:SEC`` — block step S for SEC seconds before the heartbeat
  (the wedged-dispatch mode the watchdog exists for).
- ``corrupt_sample@I`` — dataset index I raises at fetch time
  (truncated JPEG, bitrot) — exercised through GuardedDataset.
- ``truncate_ckpt@S`` — right after the save of step S finalizes,
  truncate its step dir the way a mid-finalize preemption does.

Serve-tier faults (the chaos suite's hand on a REPLICA — addressed by
the 1-based ordinal of /predict requests the process has seen, or of
engine dispatch groups for the stall; docs/SERVING.md "Failure
semantics"):

- ``serve_500@R`` / ``serve_500@RxN`` — answer HTTP 500 to the N
  (default 1) consecutive /predict requests starting at ordinal R,
  before the engine sees them (a crashed worker process behind a live
  listener; the router's 5xx retry path).
- ``serve_reset@R`` — request R gets its connection reset MID-BODY:
  response headers claim the full length, half the bytes are written,
  the socket dies (the torn-response transport-failure mode).
- ``serve_drip@R:SEC`` — request R's response body drips out over SEC
  seconds (a sick-but-alive replica; trips deadline-capped transport
  timeouts without ever refusing a connection).
- ``serve_stall@G:SEC`` — the engine's G-th dispatch group blocks SEC
  seconds before the forward (the wedged-device mode; with SEC past
  ``serve.watchdog_deadline_s`` the watchdog flips /healthz and the
  router routes around the replica).

Every fault fires ONCE per process: plans are cached per spec string,
so a supervised retry (resilience/supervisor.py) re-runs clean — the
transient-fault model the chaos suite asserts recovery under.  All
injection points are no-ops (a dict lookup) when ``DSOD_FAULTS`` is
unset; production pays nothing.
"""

from __future__ import annotations

import os
import signal
import threading
import time
from typing import Dict, List, Optional, Set, Tuple

from ..utils.logging import get_logger

ENV_VAR = "DSOD_FAULTS"

KINDS = ("nan_grad", "sigterm", "stall", "corrupt_sample", "truncate_ckpt",
         "serve_500", "serve_reset", "serve_drip", "serve_stall")


class InjectedSampleCorruption(RuntimeError):
    """Raised by the data path for an injected corrupt sample."""


class FaultPlan:
    """A parsed, latching fault schedule."""

    def __init__(self, spec: str):
        self.spec = spec
        self.nan_steps: Set[int] = set()
        self.sigterm_steps: Set[int] = set()
        self.stall_steps: Dict[int, float] = {}
        self.corrupt_indices: Set[int] = set()
        self.truncate_steps: Set[int] = set()
        # Serve tier: keyed by the 1-based /predict request ordinal
        # (dispatch-group ordinal for serve_stall).  The counters are
        # lock-guarded — HTTP handler threads all consult one plan.
        self.serve_500: Set[int] = set()
        self.serve_reset: Set[int] = set()
        self.serve_drip: Dict[int, float] = {}
        self.serve_stall: Dict[int, float] = {}
        self._serve_lock = threading.Lock()
        self._serve_seq = 0  # /predict requests seen
        self._dispatch_seq = 0  # engine dispatch groups seen
        self.fired: List[str] = []  # audit log, asserted in tests
        for part in filter(None, (p.strip() for p in spec.split(","))):
            kind, _, where = part.partition("@")
            if kind not in KINDS or not where:
                raise ValueError(
                    f"bad fault spec {part!r} (kinds: {', '.join(KINDS)}; "
                    "syntax kind@step, nan_grad@SxN, stall@S:SEC, "
                    "serve_500@RxN, serve_reset@R, serve_drip@R:SEC, "
                    "serve_stall@G:SEC)")
            if kind == "nan_grad":
                s, _, n = where.partition("x")
                for k in range(int(n or 1)):
                    self.nan_steps.add(int(s) + k)
            elif kind == "sigterm":
                self.sigterm_steps.add(int(where))
            elif kind == "stall":
                s, _, sec = where.partition(":")
                self.stall_steps[int(s)] = float(sec or 30.0)
            elif kind == "corrupt_sample":
                self.corrupt_indices.add(int(where))
            elif kind == "truncate_ckpt":
                self.truncate_steps.add(int(where))
            elif kind == "serve_500":
                s, _, n = where.partition("x")
                for k in range(int(n or 1)):
                    self.serve_500.add(int(s) + k)
            elif kind == "serve_reset":
                self.serve_reset.add(int(where))
            elif kind == "serve_drip":
                s, _, sec = where.partition(":")
                self.serve_drip[int(s)] = float(sec or 1.0)
            elif kind == "serve_stall":
                s, _, sec = where.partition(":")
                self.serve_stall[int(s)] = float(sec or 30.0)

    def _fire(self, tag: str) -> None:
        self.fired.append(tag)
        get_logger().warning("FAULT INJECTED: %s", tag)

    # -- injection points (each latches: one firing per plan) ---------

    def maybe_poison_batch(self, step: int, batch):
        """NaN one image pixel at a scheduled step (device-side edit;
        works on replicated and batch-sharded global arrays)."""
        if step not in self.nan_steps:
            return batch
        self.nan_steps.discard(step)
        self._fire(f"nan_grad@{step}")
        out = dict(batch)
        img = out["image"]
        zero = (0,) * img.ndim
        out["image"] = img.at[zero].set(float("nan"))
        return out

    def maybe_stall(self, step: int) -> None:
        sec = self.stall_steps.pop(step, None)
        if sec is not None:
            self._fire(f"stall@{step}:{sec}")
            time.sleep(sec)

    def maybe_sigterm(self, step: int) -> None:
        if step in self.sigterm_steps:
            self.sigterm_steps.discard(step)
            self._fire(f"sigterm@{step}")
            os.kill(os.getpid(), signal.SIGTERM)

    def maybe_truncate_ckpt(self, step: int, step_dir: str) -> bool:
        if step not in self.truncate_steps:
            return False
        self.truncate_steps.discard(step)
        self._fire(f"truncate_ckpt@{step}")
        from .integrity import truncate_step_dir

        truncate_step_dir(step_dir)
        return True

    # -- serve tier ----------------------------------------------------

    def next_serve_request(self) -> Optional[Tuple[str, float]]:
        """Consulted by the HTTP front end once per /predict request:
        advances the request ordinal and returns the scheduled fault
        action ``(kind, arg)`` — ``("500", 0)``, ``("reset", 0)`` or
        ``("drip", seconds)`` — or None.  Latches per ordinal."""
        with self._serve_lock:
            self._serve_seq += 1
            seq = self._serve_seq
            if seq in self.serve_500:
                self.serve_500.discard(seq)
                action = ("500", 0.0)
            elif seq in self.serve_reset:
                self.serve_reset.discard(seq)
                action = ("reset", 0.0)
            elif seq in self.serve_drip:
                action = ("drip", self.serve_drip.pop(seq))
            else:
                return None
        self._fire(f"serve_{action[0]}@{seq}"
                   + (f":{action[1]:g}" if action[0] == "drip" else ""))
        return action

    def maybe_stall_serve_dispatch(self) -> None:
        """Consulted by the engine once per dispatch group: blocks the
        scheduled group SEC seconds before its forward (the wedged-
        device serve mode — the watchdog's beat stops meanwhile)."""
        with self._serve_lock:
            self._dispatch_seq += 1
            sec = self.serve_stall.pop(self._dispatch_seq, None)
            seq = self._dispatch_seq
        if sec is not None:
            self._fire(f"serve_stall@{seq}:{sec:g}")
            time.sleep(sec)

    def check_sample(self, index: int) -> None:
        """Raise for an injected corrupt sample (consulted by
        GuardedDataset on every fetch; latches per index)."""
        if int(index) in self.corrupt_indices:
            self.corrupt_indices.discard(int(index))
            self._fire(f"corrupt_sample@{index}")
            raise InjectedSampleCorruption(
                f"injected corruption at dataset index {index}")


# Plans latch per PROCESS, not per fit() call: a supervised retry must
# see the already-spent schedule, or the "transient" fault would
# re-fire forever and no retry budget could ever converge.
_PLANS: Dict[str, FaultPlan] = {}


def plan_from_env(env: Optional[dict] = None) -> Optional[FaultPlan]:
    from ..utils import envvars

    spec = (envvars.read(ENV_VAR, env=env) or "").strip()
    if not spec:
        return None
    if spec not in _PLANS:
        _PLANS[spec] = FaultPlan(spec)
    return _PLANS[spec]


def reset_plans() -> None:
    """Forget all latched plans (test isolation)."""
    _PLANS.clear()
