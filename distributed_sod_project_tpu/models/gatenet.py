"""GateNet — gated encoder→decoder information flow for SOD.

TPU-native re-design following the paper description of "Suppress and
Balance: A Simple Gated Network for Salient Object Detection" (ECCV
2020, Zhao et al. — lartpang is an author, which is why this member
belongs in a Distributed-SOD-Project parity zoo; SURVEY.md §2 C5 names
the reference zoo and this extends it).  The reference mount was
unreadable (SURVEY.md banner), so as with the rest of the zoo the
module follows the paper's architectural signature, implemented
TPU-first:

- backbone (VGG16 / ResNet50) → 5-level pyramid, per-level 3×3
  transfer convs to a fixed decoder width.
- **gate units**: at every skip connection a sigmoid gate computed
  from (encoder feature, upsampled decoder state) multiplicatively
  suppresses background activations before the skip enters the
  decoder — the paper's core idea (balance information flow between
  levels instead of passing raw skips).
- **dilated-pyramid bridge** on the deepest level standing in for the
  paper's Fold-ASPP: parallel 3×3 convs at dilations (1, 2, 4, 6)
  plus a global-context branch, concatenated and fused 1×1.  The
  paper's "fold" im2col step is a gather-heavy op that maps poorly to
  the MXU; dilated convs express the same receptive-field pyramid as
  native XLA convolutions (documented TPU-first substitution, same
  posture as HDFNet's im2col+einsum dynamic filters).
- **dual-branch heads with deep supervision**: every decoder stage
  emits a side logit (5 outputs); element 0 is the finest/primary —
  the zoo-uniform list-of-logits contract.

Conventions: NHWC, bf16 compute / f32 params, cross-replica BN via
``axis_name`` (SyncBN parity), all resizes static-shape.
"""

from __future__ import annotations

from typing import Any, List, Optional

import jax.numpy as jnp
from flax import linen as nn

from .backbones import ResNet50, VGG16
from .layers import ConvBNAct, resize_to, upsample_like


class GateUnit(nn.Module):
    """Multiplicative skip gate: sigmoid over a fused (enc, dec) view
    suppresses encoder activations the decoder state marks as
    background."""

    axis_name: Optional[str] = None
    bn_momentum: float = 0.9
    conv_impl: Optional[str] = None
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, enc, dec, train: bool = False):
        # (enc, dec) convolve as their channel concat — the ConvBNAct
        # seam fuses the concat away on the fused arm.
        gate = ConvBNAct(enc.shape[-1], (3, 3), act=None,
                         axis_name=self.axis_name,
                         bn_momentum=self.bn_momentum,
                         conv_impl=self.conv_impl, dtype=self.dtype,
                         param_dtype=self.param_dtype)([enc, dec],
                                                       train=train)
        return enc * nn.sigmoid(gate)


class DilatedPyramidBridge(nn.Module):
    """ASPP-style bridge: dilations (1, 2, 4, 6) + global context."""

    width: int
    axis_name: Optional[str] = None
    bn_momentum: float = 0.9
    conv_impl: Optional[str] = None
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        kw = dict(axis_name=self.axis_name, bn_momentum=self.bn_momentum,
                  conv_impl=self.conv_impl,
                  dtype=self.dtype, param_dtype=self.param_dtype)
        branches = [
            ConvBNAct(self.width, (3, 3), dilation=d, **kw)(x, train=train)
            for d in (1, 2, 4, 6)
        ]
        # Global-context branch: pooled statistics broadcast back.
        g = jnp.mean(x, axis=(1, 2), keepdims=True)
        g = ConvBNAct(self.width, (1, 1), **kw)(g, train=train)
        branches.append(jnp.broadcast_to(
            g, x.shape[:3] + (self.width,)).astype(g.dtype))
        return ConvBNAct(self.width, (1, 1), **kw)(branches, train=train)


class GateNet(nn.Module):
    """Gated SOD network.  Returns five logits (finest first)."""

    backbone: str = "vgg16"
    backbone_bn: bool = True
    width: int = 64
    axis_name: Optional[str] = None
    bn_momentum: float = 0.9
    # Decoder resample strategy (model.resample_impl): fast | xla |
    # convt | fused.  GateNet's decoder reuses the upsampled state
    # twice (gate input AND skip concat), so the fused arm runs the
    # BARE single-pass upsample kernel (no merge epilogue) here.
    resample_impl: str = "fast"
    # Conv-block strategy (model.conv_impl): xla | fused — see
    # layers.ConvBNAct; threaded to every conv block, backbone included.
    conv_impl: Optional[str] = None
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, image, depth=None, *,
                 train: bool = False) -> List[jnp.ndarray]:
        del depth  # RGB-only member; uniform zoo signature
        x = image.astype(self.dtype)
        bkw = dict(axis_name=self.axis_name, bn_momentum=self.bn_momentum,
                   conv_impl=self.conv_impl,
                   dtype=self.dtype, param_dtype=self.param_dtype)
        if self.backbone == "vgg16":
            feats = VGG16(use_bn=self.backbone_bn, **bkw)(x, train=train)
        elif self.backbone == "resnet50":
            feats = ResNet50(**bkw)(x, train=train)
        else:
            raise ValueError(f"GateNet: unknown backbone {self.backbone!r}")

        kw = dict(axis_name=self.axis_name, bn_momentum=self.bn_momentum,
                  conv_impl=self.conv_impl,
                  dtype=self.dtype, param_dtype=self.param_dtype)
        # Per-level transfer convs to the decoder width.
        trans = [ConvBNAct(self.width, (3, 3), **kw)(f, train=train)
                 for f in feats]

        d = DilatedPyramidBridge(self.width, **kw)(trans[-1], train=train)
        logits: List[jnp.ndarray] = []

        def side_logit(feat):
            l = nn.Conv(1, (3, 3), padding="SAME", dtype=self.dtype,
                        param_dtype=self.param_dtype)(feat)
            return resize_to(l, image.shape[1:3],
                             impl=self.resample_impl).astype(jnp.float32)

        logits.append(side_logit(d))  # coarsest
        for i in range(len(trans) - 2, -1, -1):
            up = upsample_like(d, trans[i], impl=self.resample_impl)
            gated = GateUnit(**kw)(trans[i], up, train=train)
            d = ConvBNAct(self.width, (3, 3), **kw)([gated, up],
                                                    train=train)
            logits.append(side_logit(d))

        # Zoo contract: element 0 is the primary (finest) prediction.
        return logits[::-1]
