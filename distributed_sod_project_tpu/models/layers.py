"""Shared NHWC building blocks for the model zoo.

TPU-first conventions used throughout the zoo:

- NHWC layout (the XLA:TPU-native conv layout; channels land on the
  128-wide lane dimension of the MXU/VPU).
- ``dtype`` (compute) defaults to bfloat16 with float32 params — convs
  and matmuls run on the MXU in bf16, BatchNorm statistics and the loss
  are reduced in float32.
- Cross-replica BatchNorm via linen's ``axis_name``: inside a
  ``shard_map`` over the ``data`` mesh axis this psums batch statistics
  across replicas, which is the XLA-native form of the SyncBN the
  reference got from DDP (SURVEY.md §2.3, §7.3 hard part 3).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence, Tuple

import jax.numpy as jnp
from flax import linen as nn

Dtype = Any

CONV_IMPLS = ("xla", "fused")


def _resolve_conv_impl(impl: Optional[str]) -> str:
    """Resolve the conv-block execution strategy (``model.conv_impl``,
    threaded through the zoo as an explicit ``conv_impl``).  Unlike the
    resample knob there is no env alias — the config is the only
    selector; ``DSOD_CONV_VMEM_MB`` tunes the kernel, never selects it."""
    if impl is None:
        return "xla"
    if impl not in CONV_IMPLS:
        raise ValueError(
            f"conv impl must be one of {CONV_IMPLS}, got {impl!r}")
    return impl


class _FusedConvParams(nn.Module):
    """Parameter holder for the fused conv branch, named ``Conv_0`` so
    the param tree is byte-for-byte what ``nn.Conv`` declares on the
    XLA branch (same initializers, same RNG fold path) — a checkpoint
    trained at either ``conv_impl`` restores into the other.  Also the
    read point for the serve-precision quantized view: when the apply
    variables carry a ``quant_scales`` collection (built by
    ``serve/precision.fused_conv_cast_variables``), the kernel param
    itself is the int8/fp8 leaf and the per-channel dequant scale rides
    back alongside it."""

    features: int
    kernel: Tuple[int, int]
    in_features: int
    use_bias: bool
    param_dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self):
        k = self.param(
            "kernel", nn.initializers.lecun_normal(),
            tuple(self.kernel) + (self.in_features, self.features),
            self.param_dtype)
        b = self.param(
            "bias", nn.initializers.zeros_init(), (self.features,),
            self.param_dtype) if self.use_bias else None
        s = None
        if self.has_variable("quant_scales", "kernel"):
            s = self.get_variable("quant_scales", "kernel")
        return k, b, s


class _FusedBNParams(nn.Module):
    """Inference-mode BatchNorm parameter holder, named
    ``BatchNorm_0`` with flax's exact names/shapes/dtypes (scale/bias
    in params at ``param_dtype``; mean/var in batch_stats at f32) so
    the fused fold and the real ``nn.BatchNorm`` share one state."""

    features: int
    param_dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self):
        scale = self.param("scale", nn.initializers.ones_init(),
                           (self.features,), self.param_dtype)
        bias = self.param("bias", nn.initializers.zeros_init(),
                          (self.features,), self.param_dtype)
        mean = self.variable(
            "batch_stats", "mean",
            lambda: jnp.zeros((self.features,), jnp.float32))
        var = self.variable(
            "batch_stats", "var",
            lambda: jnp.ones((self.features,), jnp.float32))
        return scale, bias, mean.value, var.value


class ConvBNAct(nn.Module):
    """Conv → (BatchNorm) → (activation), NHWC.

    THE conv-block seam of the zoo: every encoder/decoder block in the
    four decoder families (and the VGG/ResNet backbones) routes here,
    so ``model.conv_impl`` selects one execution strategy zoo-wide:

    - ``xla`` (default) — ``nn.Conv`` + ``nn.BatchNorm`` exactly as
      before the knob existed (the lowered program is byte-identical,
      asserted in tests/test_pallas_conv.py);
    - ``fused`` — the Pallas conv-stage kernel
      (``pallas/fused_conv.py``): conv + inference-mode-BN + ReLU as
      ONE VMEM pass per image, and — when ``x`` is a list/tuple of
      same-spatial maps — conv over their channel concat WITHOUT
      materializing the concat in HBM (the decoder-head idiom).
      Train-mode BatchNorm needs whole-batch statistics (plus the
      cross-replica ``axis_name`` psum), so those sites run the fused
      conv kernel followed by the real ``nn.BatchNorm``; sites outside
      the kernel's envelope (stride > 1, even kernels, VMEM budget —
      ``fused_conv_available``) fall back to the XLA math PER-SITE
      with a trace-time log line, mirroring ``resample_merge``.

    Either impl accepts a list/tuple input as "concat these along
    channels first" — on the XLA path that is a plain
    ``jnp.concatenate`` where the caller used to do it.
    """

    features: int
    kernel: Tuple[int, int] = (3, 3)
    strides: int = 1
    dilation: int = 1
    use_bn: bool = True
    act: Optional[Callable] = nn.relu
    axis_name: Optional[str] = None  # cross-replica BN axis (e.g. "data")
    bn_momentum: float = 0.9
    conv_impl: Optional[str] = None  # None/"xla" | "fused"
    dtype: Dtype = jnp.float32
    param_dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        impl = _resolve_conv_impl(self.conv_impl)
        if impl == "fused":
            parts = list(x) if isinstance(x, (list, tuple)) else [x]
            return self._fused_branch(parts, train)
        if isinstance(x, (list, tuple)):
            x = x[0] if len(x) == 1 else jnp.concatenate(x, axis=-1)
        # Explicit symmetric padding (= torch's padding=k//2·dilation).
        # XLA's "SAME" pads (0,1) at stride 2 — one pixel off from the
        # torch alignment ImageNet weights were trained with, which
        # would silently degrade every ported backbone.  Identical to
        # SAME at stride 1 with odd kernels.
        if self.kernel[0] % 2 and self.kernel[1] % 2:
            pad = [(self.dilation * (k // 2),) * 2 for k in self.kernel]
        else:
            pad = "SAME"
        x = nn.Conv(
            self.features,
            self.kernel,
            strides=(self.strides, self.strides),
            kernel_dilation=(self.dilation, self.dilation),
            padding=pad,
            use_bias=not self.use_bn,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
        )(x)
        if self.use_bn:
            x = nn.BatchNorm(
                use_running_average=not train,
                momentum=self.bn_momentum,
                axis_name=self.axis_name if train else None,
                dtype=self.dtype,
                param_dtype=self.param_dtype,
            )(x)
        if self.act is not None:
            x = self.act(x)
        return x

    def _fused_branch(self, parts, train: bool):
        """The ``conv_impl=fused`` arm: fused Pallas kernel where the
        site fits, the same XLA math on the same (self-held) params
        per-site otherwise."""
        import jax.lax as lax

        from ..pallas import fused_conv as fc

        # Marker for the serve-precision quantized-view builder
        # (``fused_conv_cast_variables``): a mutable 'dsod_fused_conv'
        # collection collects the scopes whose Conv_0/kernel this seam
        # consumes (and therefore may stay int8/fp8).  A no-op on every
        # normal apply (the collection is immutable/absent); guarded
        # out of init, where EVERY collection is mutable and the marker
        # would otherwise pollute the init tree.
        if not self.is_initializing():
            self.sow("dsod_fused_conv", "site", jnp.zeros((), jnp.int32))
        kh, kw = self.kernel
        cin = sum(p.shape[-1] for p in parts)
        kernel, bias, qscale = _FusedConvParams(
            features=self.features, kernel=self.kernel, in_features=cin,
            use_bias=not self.use_bn, param_dtype=self.param_dtype,
            name="Conv_0")()
        cd = self.dtype
        fits = (self.strides == 1 and kh % 2 == 1 and kw % 2 == 1
                and fc.fused_conv_available(
                    [tuple(p.shape) for p in parts], (kh, kw),
                    self.dilation, self.features))
        if not fits:
            # Out of envelope: trace-time note so a fused A/B leg knows
            # which sites opted out (fires once per compile, not per
            # step) — the resample_merge fallback pattern.
            import logging

            logging.getLogger(__name__).debug(
                "fused conv out of envelope at %s (k=%s stride=%s "
                "dil=%s -> %dch): xla path",
                [tuple(p.shape) for p in parts], self.kernel,
                self.strides, self.dilation, self.features)
            return self._xla_conv_on_params(parts, kernel, bias, qscale,
                                            train)
        relu_in_kernel = self.act is nn.relu
        xs = tuple(p.astype(cd) for p in parts)
        vecs = {}
        if qscale is not None:
            vecs["qscale"] = jnp.asarray(qscale, jnp.float32).reshape(-1)
            wk = kernel  # int8/fp8 leaf: dequantized in-VMEM
        else:
            wk = kernel.astype(cd)  # nn.Conv's promote_dtype cast
        mode = "none"
        if self.use_bn and not train:
            scale, beta, mean, var = _FusedBNParams(
                features=self.features, param_dtype=self.param_dtype,
                name="BatchNorm_0")()
            # flax _normalize's exact op order (epsilon included), so
            # the fold is the SAME f32 values BatchNorm would compute.
            mul = lax.rsqrt(var + 1e-5)
            mul = mul * scale
            vecs.update(mean=mean, mul=mul, bias=beta)
            mode = "bn"
        elif not self.use_bn:
            vecs["bias"] = bias.astype(cd)
            mode = "bias"
        y = fc.fused_conv(
            xs, wk, vecs, kernel=self.kernel, dilation=self.dilation,
            mode=mode, relu=(mode != "none" and relu_in_kernel))
        if mode == "none":
            # Train-mode BN: batch statistics (and the cross-replica
            # psum) need the whole batch — the kernel fuses the conv,
            # flax's BatchNorm follows it unchanged.
            y = nn.BatchNorm(
                use_running_average=not train,
                momentum=self.bn_momentum,
                axis_name=self.axis_name if train else None,
                dtype=self.dtype,
                param_dtype=self.param_dtype,
                name="BatchNorm_0",
            )(y)
        if self.act is not None and not (mode != "none" and relu_in_kernel):
            y = self.act(y)
        return y

    def _xla_conv_on_params(self, parts, kernel, bias, qscale,
                            train: bool):
        """Per-site fallback inside the fused branch: ``nn.Conv``'s
        exact math (promote/pad/conv/bias order replicated) on the
        branch's own params — needed because a quantized view's int8
        kernel leaf must be dequantized densely here, which ``nn.Conv``
        cannot do."""
        import jax.lax as lax
        from flax.linen.dtypes import promote_dtype

        x = parts[0] if len(parts) == 1 else jnp.concatenate(parts, -1)
        if qscale is not None:
            kernel = kernel.astype(jnp.float32) * qscale
        if self.kernel[0] % 2 and self.kernel[1] % 2:
            pad = [(self.dilation * (k // 2),) * 2 for k in self.kernel]
        else:
            pad = "SAME"
        x, kernel, bias = promote_dtype(x, kernel, bias, dtype=self.dtype)
        y = lax.conv_general_dilated(
            x, kernel, (self.strides, self.strides), pad,
            rhs_dilation=(self.dilation, self.dilation),
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if bias is not None:
            y = y + bias.reshape((1,) * (y.ndim - 1) + (-1,))
        if self.use_bn:
            y = nn.BatchNorm(
                use_running_average=not train,
                momentum=self.bn_momentum,
                axis_name=self.axis_name if train else None,
                dtype=self.dtype,
                param_dtype=self.param_dtype,
                name="BatchNorm_0",
            )(y)
        if self.act is not None:
            y = self.act(y)
        return y


def max_pool(x, window: int = 2, stride: int = 2):
    return nn.max_pool(x, (window, window), strides=(stride, stride), padding="SAME")


class _S2DConv7x7(nn.Module):
    """7×7/stride-2 conv computed as space-to-depth + 4×4/stride-1.

    The MLPerf-ResNet TPU trick: a stride-2 conv on a 3-channel
    full-res image keeps the MXU's 128-lane input dimension 97% idle
    and streams the largest activation in the network from HBM.
    Re-expressing it over the 2×2-block space-to-depth input
    ([B,H/2,W/2,12]) quadruples the contraction depth and quarters the
    streamed rows, with IDENTICAL arithmetic: the stored parameter
    stays the standard ``kernel`` [7,7,C,F] (checkpoint- and
    weight-port-compatible), padded to 8×8 with a leading zero row/col
    and regrouped at trace time so tap (u,v) lands on the s2d channel
    of its parity.  Derivation: with torch padding 3, tap u = 2p+a−1
    reads x[2(i+p−2)+a] = s2d row i+p−2, parity a — hence the 4-tap
    kernel and explicit (2,1) padding.  Bit-equivalence vs the plain
    stem is asserted in tests/test_models.py.
    """

    features: int
    dtype: Dtype = jnp.float32
    param_dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        import jax.lax as lax

        b, h, w, c = x.shape
        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(),
            (7, 7, c, self.features), self.param_dtype)
        k = jnp.pad(kernel, ((1, 0), (1, 0), (0, 0), (0, 0)))
        k = (k.reshape(4, 2, 4, 2, c, self.features)
             .transpose(0, 2, 1, 3, 4, 5)
             .reshape(4, 4, 4 * c, self.features))
        x2 = (x.reshape(b, h // 2, 2, w // 2, 2, c)
              .transpose(0, 1, 3, 2, 4, 5)
              .reshape(b, h // 2, w // 2, 4 * c))
        return lax.conv_general_dilated(
            x2.astype(self.dtype), k.astype(self.dtype),
            window_strides=(1, 1), padding=((2, 1), (2, 1)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"))


class SpaceToDepthStem(nn.Module):
    """Drop-in for ``ConvBNAct(F, (7,7), strides=2)`` with the conv
    computed via :class:`_S2DConv7x7`.  Instantiate with
    ``name="ConvBNAct_0"`` so the param tree is indistinguishable from
    the plain stem (children ``Conv_0`` / ``BatchNorm_0``) — a
    checkpoint trained either way restores into the other."""

    features: int
    axis_name: Optional[str] = None
    bn_momentum: float = 0.9
    act: Optional[Callable] = nn.relu
    dtype: Dtype = jnp.float32
    param_dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = _S2DConv7x7(self.features, dtype=self.dtype,
                        param_dtype=self.param_dtype, name="Conv_0")(x)
        x = nn.BatchNorm(
            use_running_average=not train,
            momentum=self.bn_momentum,
            axis_name=self.axis_name if train else None,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            name="BatchNorm_0",
        )(x)
        if self.act is not None:
            x = self.act(x)
        return x


def _upsample_axis(x, axis: int, s: int):
    """Integer-factor bilinear upsample along one spatial axis.

    Numerically identical to ``jax.image.resize(method='bilinear')``
    (half-pixel centers; at the edges the out-of-range tap's weight is
    renormalised away, which for a 2-tap kernel equals index clamping):
    ``out[s*i + p] = (1-f_p)*x[i + d_p] + f_p*x[i + d_p + 1]`` with the
    phase constants baked in at trace time.  Pure slice/lerp/interleave
    — a single VPU pass, where the generic resize lowers to per-axis
    ``dot_general``s whose operand layouts cost two relayout copies per
    call (measured 15% of the MINet-R50 train step on v5e;
    docs/PERFORMANCE.md).

    The interleave is LAYOUT-STABLE (round 5): the phases concatenate
    along the NEXT axis and one reshape merges the pair — by row-major
    identity ``(…, n, s·m, …) == (…, n, s, m, …) == (…, s·n, m, …)``
    this produces exactly the same elements as the historical
    ``stack(axis+1) + reshape`` form, but without inserting size-1 axes
    XLA:TPU answers with dim-shuffled relayout copies (~1.25 ms per
    call on ``bf16[64,160,64,160]`` in the round-2 v5e trace, ~10% of
    the flagship step in data-formatting total).  Bit-identical either
    way; ``DSOD_RESIZE_INTERLEAVE=stack`` keeps the old form as the A/B
    arm ``tools/hlo_guard.py`` diffs against.
    """
    import jax.lax as lax

    n = x.shape[axis]
    first = lax.slice_in_dim(x, 0, 1, axis=axis)
    last = lax.slice_in_dim(x, n - 1, n, axis=axis)
    left = jnp.concatenate(
        [first, lax.slice_in_dim(x, 0, n - 1, axis=axis)], axis)
    right = jnp.concatenate(
        [lax.slice_in_dim(x, 1, n, axis=axis), last], axis)
    phases = []
    for p in range(s):
        c = (p + 0.5) / s - 0.5
        if c < 0:  # taps x[i-1], x[i]
            a, b, f = left, x, c + 1.0
        else:  # taps x[i], x[i+1]
            a, b, f = x, right, c
        f = jnp.asarray(f, x.dtype)
        phases.append(a * (1 - f) + b * f)
    out_shape = x.shape[:axis] + (n * s,) + x.shape[axis + 1:]
    from ..utils import envvars

    if (axis + 1 >= x.ndim
            or envvars.read("DSOD_RESIZE_INTERLEAVE") == "stack"):
        y = jnp.stack(phases, axis=axis + 1)  # historical form
    else:
        y = jnp.concatenate(phases, axis=axis + 1)  # layout-stable
    return y.reshape(out_shape)


def _downsample2_axis(x, axis: int):
    """Antialiased factor-2 bilinear downsample along one spatial axis.

    Matches ``jax.image.resize``'s default (antialias=True) triangle
    kernel [1,3,3,1]/8 at half-pixel phase, with the edge rows
    renormalised over their in-range taps exactly as the reference
    implementation does (verified by impulse response — the edge sum is
    7/8, hence the /0.875).
    """
    import jax.lax as lax

    n = x.shape[axis]
    xe = lax.slice_in_dim(x, 0, n, stride=2, axis=axis)  # x[2i]
    xo = lax.slice_in_dim(x, 1, n, stride=2, axis=axis)  # x[2i+1]
    m = n // 2
    if m == 1:  # both outer taps cut: renorm [_,3,3,_]/6 = plain mean
        return (xe + xo) * jnp.asarray(0.5, x.dtype)
    zero_first = jnp.zeros_like(lax.slice_in_dim(xo, 0, 1, axis=axis))
    xo_m1 = jnp.concatenate(  # x[2i-1]; cut tap at i=0
        [zero_first, lax.slice_in_dim(xo, 0, m - 1, axis=axis)], axis)
    xe_p1 = jnp.concatenate(  # x[2i+2]; cut tap at i=m-1
        [lax.slice_in_dim(xe, 1, m, axis=axis), zero_first], axis)
    w1, w3 = jnp.asarray(0.125, x.dtype), jnp.asarray(0.375, x.dtype)
    y = w1 * xo_m1 + w3 * xe + w3 * xo + w1 * xe_p1
    renorm = jnp.asarray(1.0 / 0.875, x.dtype)
    return jnp.concatenate([
        lax.slice_in_dim(y, 0, 1, axis=axis) * renorm,
        lax.slice_in_dim(y, 1, m - 1, axis=axis),
        lax.slice_in_dim(y, m - 1, m, axis=axis) * renorm,
    ], axis)


def _upsample2_axis_convt(x, axis: int):
    """Factor-2 bilinear upsample as a depthwise fractionally-strided
    conv — the ``DSOD_RESIZE_IMPL=convt`` A/B arm.

    Same numerics as :func:`_upsample_axis` (s=2): the two output
    phases 0.25·x[i-1]+0.75·x[i] and 0.75·x[i]+0.25·x[i+1] are exactly
    one length-4 kernel [.25,.75,.75,.25] cross-correlated over the
    2×-lhs-dilated input; replicate-padding one row each side makes
    the conv's implicit zero taps reproduce the edge clamping, and
    VALID output length lands on 2n with no crop (derivation in the
    round-4 notes, docs/PERFORMANCE.md).

    Why it might win: the round-2 v5e trace shows the stack+reshape
    interleave of ``_upsample_axis`` costing ~1.25 ms relayout copies
    per call at b64 (data-formatting = 10% of the step) — a conv's
    output needs no relayout.  Why it might lose: depthwise convs run
    on the VPU with kernel overhead per channel.  Hardware A/B leg:
    ``rsz_convt`` in tools/tpu_agenda_r4.sh.
    """
    import jax.lax as lax

    n = x.shape[axis]
    first = lax.slice_in_dim(x, 0, 1, axis=axis)
    last = lax.slice_in_dim(x, n - 1, n, axis=axis)
    xp = jnp.concatenate([first, x, last], axis=axis)
    c = x.shape[-1]
    k = jnp.asarray([0.25, 0.75, 0.75, 0.25], x.dtype)
    if axis == 1:
        kern = jnp.tile(k.reshape(4, 1, 1, 1), (1, 1, 1, c))
        dil = (2, 1)
        pad = ((0, 0), (0, 0))
    else:
        kern = jnp.tile(k.reshape(1, 4, 1, 1), (1, 1, 1, c))
        dil = (1, 2)
        pad = ((0, 0), (0, 0))
    return lax.conv_general_dilated(
        xp, kern, window_strides=(1, 1), padding=pad,
        lhs_dilation=dil, dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=c)


RESAMPLE_IMPLS = ("fast", "xla", "convt", "fused")


def _resolve_resample_impl(impl: Optional[str]) -> str:
    """Resolve the execution strategy for a resample call.

    ``model.resample_impl`` (threaded through the decoder modules as an
    explicit ``impl``) subsumes the ``DSOD_RESIZE_IMPL`` env knob: an
    explicit non-default impl always wins; at the default (``None`` /
    ``"fast"``) a set env var still selects the arm, so the recorded
    A/B legs (``rsz_convt`` etc. in tools/tpu_agenda_r4.sh) and the
    BASELINE.md measurement commands keep working unchanged.
    """
    from ..utils import envvars

    if impl in (None, "fast"):
        impl = envvars.read("DSOD_RESIZE_IMPL") or "fast"
    if impl not in RESAMPLE_IMPLS:
        raise ValueError(
            f"resample impl must be one of {RESAMPLE_IMPLS}, got {impl!r}")
    return impl


def _fast_bilinear_axis(x, axis: int, out_n: int, impl: str = "fast"):
    """One axis of ``resize_to``'s fast path; None if unsupported."""
    n = x.shape[axis]
    if out_n == n:
        return x
    if out_n % n == 0:
        s = out_n // n
        if s == 2 and impl == "convt":
            return _upsample2_axis_convt(x, axis)
        return _upsample_axis(x, axis, s)
    if n == 2 * out_n and n % 2 == 0:
        return _downsample2_axis(x, axis)
    return None


def resize_to(x, hw: Tuple[int, int], method: str = "bilinear",
              impl: Optional[str] = None):
    """Static-shape spatial resize (the upsample path of every decoder).

    Bilinear integer-factor resizes — every resize the zoo performs —
    take the fused slice/lerp path above; anything else falls back to
    ``jax.image.resize`` (same numerics either way, asserted in
    tests/test_models.py).  ``impl`` (default: ``DSOD_RESIZE_IMPL``,
    else ``fast``) selects the execution strategy:

    - ``fast``  — slice/lerp with the layout-stable interleave;
    - ``xla``   — force the generic ``jax.image.resize`` everywhere
      (the measurement/debug escape hatch behind the BASELINE.md
      numbers);
    - ``convt`` — 2x upsamples as depthwise fractionally-strided convs;
    - ``fused`` — exact-2x upsamples as one Pallas VMEM pass
      (``pallas/fused_resample.py``) where the shape/VMEM budget
      allows, the ``fast`` path otherwise.

    Every arm computes the same bilinear resample; ``fast``/``convt``
    match bitwise, ``xla``/``fused`` to dtype round-off (the fused
    kernel lerps in f32 in-kernel, so under bf16 compute it is the
    MORE precise arm, not a bit-equal one).
    """
    import jax

    impl = _resolve_resample_impl(impl)
    if method == "bilinear" and impl != "xla":
        if impl == "fused":
            from ..pallas.fused_resample import (fused_resample_available,
                                                 fused_upsample2)

            if fused_resample_available(x.shape, hw):
                return fused_upsample2(x)
        h = _fast_bilinear_axis(x, 1, hw[0], impl)
        if h is not None:
            w = _fast_bilinear_axis(h, 2, hw[1], impl)
            if w is not None:
                return w
    out = jax.image.resize(x, (x.shape[0], hw[0], hw[1], x.shape[3]), method=method)
    return out.astype(x.dtype)


def upsample_like(x, ref, method: str = "bilinear",
                  impl: Optional[str] = None):
    """Resize ``x`` to the spatial size of ``ref``."""
    return resize_to(x, (ref.shape[1], ref.shape[2]), method=method,
                     impl=impl)


def resample_merge(x, lateral, mode: str = "add", x_first: bool = True,
                   impl: Optional[str] = None):
    """The decoder-stage idiom: upsample ``x`` to ``lateral``'s spatial
    size and merge — ``mode='add'`` (``up + lateral``) or
    ``mode='concat'`` (``[up, lateral]`` channels when ``x_first``,
    ``[lateral, up]`` otherwise).

    All four decoder users (MINet AIM/SIM, HDFNet, GateNet via its
    bare-upsample form, U²-Net) route their merges here so the
    ``model.resample_impl`` knob selects one strategy zoo-wide.  With
    ``impl='fused'`` and an exact-2x, VMEM-sized resample the whole
    chain runs as ONE Pallas pass (the fine map is read from HBM once
    — roofline lever #1, docs/PERFORMANCE.md); any other impl, or an
    out-of-envelope shape, takes the plain resize + merge.  Every arm
    computes the same resample (≤1e-5 in f32, asserted in
    tests/test_pallas_resample.py); under bf16 compute the fused arm
    lerps in f32 in-kernel where the fast arm lerps in bf16, so the
    arms agree to bf16 round-off (~1e-3), not bitwise.
    """
    impl = _resolve_resample_impl(impl)
    if impl == "fused":
        from ..pallas.fused_resample import (fused_resample_available,
                                             fused_upsample2_merge)

        if (mode in ("add", "concat")
                and lateral.shape[0] == x.shape[0]
                and (mode != "add" or lateral.shape[-1] == x.shape[-1])
                and fused_resample_available(
                    x.shape, lateral.shape[1:3], mode, lateral.shape[-1])):
            return fused_upsample2_merge(x, lateral, mode=mode,
                                         x_first=x_first)
        # Out of envelope: trace-time note so a fused A/B leg knows
        # which sites opted out (fires once per compile, not per step),
        # then keep the EXPLICIT 'fused' selection and let resize_to
        # degrade it to the fast path itself — rewriting to 'fast'
        # would re-enter env resolution and let a stray
        # DSOD_RESIZE_IMPL hijack a site the user pinned to fused.
        import logging

        logging.getLogger(__name__).debug(
            "fused resample out of envelope at %s -> %s (%s): fast path",
            x.shape, lateral.shape, mode)
    up = resize_to(x, (lateral.shape[1], lateral.shape[2]), impl=impl)
    if mode == "add":
        return up + lateral
    if mode == "concat":
        parts = [up, lateral] if x_first else [lateral, up]
        return jnp.concatenate(parts, axis=-1)
    raise ValueError(f"mode must be 'add' or 'concat', got {mode!r}")
