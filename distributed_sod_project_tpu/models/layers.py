"""Shared NHWC building blocks for the model zoo.

TPU-first conventions used throughout the zoo:

- NHWC layout (the XLA:TPU-native conv layout; channels land on the
  128-wide lane dimension of the MXU/VPU).
- ``dtype`` (compute) defaults to bfloat16 with float32 params — convs
  and matmuls run on the MXU in bf16, BatchNorm statistics and the loss
  are reduced in float32.
- Cross-replica BatchNorm via linen's ``axis_name``: inside a
  ``shard_map`` over the ``data`` mesh axis this psums batch statistics
  across replicas, which is the XLA-native form of the SyncBN the
  reference got from DDP (SURVEY.md §2.3, §7.3 hard part 3).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence, Tuple

import jax.numpy as jnp
from flax import linen as nn

Dtype = Any


class ConvBNAct(nn.Module):
    """Conv → (BatchNorm) → (activation), NHWC."""

    features: int
    kernel: Tuple[int, int] = (3, 3)
    strides: int = 1
    dilation: int = 1
    use_bn: bool = True
    act: Optional[Callable] = nn.relu
    axis_name: Optional[str] = None  # cross-replica BN axis (e.g. "data")
    bn_momentum: float = 0.9
    dtype: Dtype = jnp.float32
    param_dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        # Explicit symmetric padding (= torch's padding=k//2·dilation).
        # XLA's "SAME" pads (0,1) at stride 2 — one pixel off from the
        # torch alignment ImageNet weights were trained with, which
        # would silently degrade every ported backbone.  Identical to
        # SAME at stride 1 with odd kernels.
        if self.kernel[0] % 2 and self.kernel[1] % 2:
            pad = [(self.dilation * (k // 2),) * 2 for k in self.kernel]
        else:
            pad = "SAME"
        x = nn.Conv(
            self.features,
            self.kernel,
            strides=(self.strides, self.strides),
            kernel_dilation=(self.dilation, self.dilation),
            padding=pad,
            use_bias=not self.use_bn,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
        )(x)
        if self.use_bn:
            x = nn.BatchNorm(
                use_running_average=not train,
                momentum=self.bn_momentum,
                axis_name=self.axis_name if train else None,
                dtype=self.dtype,
                param_dtype=self.param_dtype,
            )(x)
        if self.act is not None:
            x = self.act(x)
        return x


def max_pool(x, window: int = 2, stride: int = 2):
    return nn.max_pool(x, (window, window), strides=(stride, stride), padding="SAME")


def resize_to(x, hw: Tuple[int, int], method: str = "bilinear"):
    """Static-shape spatial resize (the upsample path of every decoder)."""
    import jax

    out = jax.image.resize(x, (x.shape[0], hw[0], hw[1], x.shape[3]), method=method)
    return out.astype(x.dtype)


def upsample_like(x, ref, method: str = "bilinear"):
    """Resize ``x`` to the spatial size of ``ref``."""
    return resize_to(x, (ref.shape[1], ref.shape[2]), method=method)
