"""Swin-SOD — transformer-encoder saliency model (stretch config [B:11]).

Swin-T pyramid (strides 4/8/16/32) + FPN-style top-down decoder:
lateral 1×1 projections, upsample-add, 3×3 smoothing per level, primary
head at stride 4, deep-supervision heads at strides 8/16.  Returns 3
logits at input resolution, element 0 primary (zoo convention).
"""

from __future__ import annotations

from typing import Any, List, Optional

import jax.numpy as jnp
from flax import linen as nn

from .backbones.swin import SwinT
from .layers import ConvBNAct, resize_to, upsample_like


class SwinSOD(nn.Module):
    width: int = 128
    axis_name: Optional[str] = None
    bn_momentum: float = 0.9
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, image, depth=None, *, train: bool = False) -> List[jnp.ndarray]:
        del depth  # RGB-only model; uniform zoo signature
        x = image.astype(self.dtype)
        feats = SwinT(dtype=self.dtype, param_dtype=self.param_dtype)(
            x, train=train)

        kw = dict(axis_name=self.axis_name, bn_momentum=self.bn_momentum,
                  dtype=self.dtype, param_dtype=self.param_dtype)
        laterals = [ConvBNAct(self.width, (1, 1), **kw)(f, train)
                    for f in feats]

        d = laterals[-1]
        sides = [d]
        for lat in laterals[-2::-1]:
            d = upsample_like(d, lat) + lat
            d = ConvBNAct(self.width, (3, 3), **kw)(d, train)
            sides.append(d)

        hw = image.shape[1:3]
        logits = []
        # Primary = finest (stride 4); aux at strides 8 and 16.
        for s in (sides[-1], sides[-2], sides[-3]):
            l = nn.Conv(1, (3, 3), padding="SAME", dtype=self.dtype,
                        param_dtype=self.param_dtype)(s)
            logits.append(resize_to(l, hw).astype(jnp.float32))
        return logits
