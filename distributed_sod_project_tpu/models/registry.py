"""Model zoo registry (SURVEY.md §2 C5).

``build_model(cfg.model)`` maps a ModelConfig onto a constructed linen
module.  Zoo-wide call convention::

    logits_list = model.apply(variables, image, depth, train=...,
                              mutable=["batch_stats"] if train else False)

where ``logits_list[0]`` is the primary full-resolution saliency logit.
"""

from __future__ import annotations

from typing import Callable, Dict

import jax.numpy as jnp

_REGISTRY: Dict[str, Callable] = {}


def register_model(name: str):
    def deco(builder: Callable):
        if name in _REGISTRY:
            raise KeyError(f"model {name!r} already registered")
        _REGISTRY[name] = builder
        return builder

    return deco


def list_models():
    return sorted(_REGISTRY)


def build_model(model_cfg):
    """Construct the linen module described by a ModelConfig."""
    if model_cfg.name not in _REGISTRY:
        raise KeyError(
            f"unknown model {model_cfg.name!r}; known: {list_models()}"
        )
    if model_cfg.attn_impl != "xla" and model_cfg.name != "vit_sod":
        # Loud instead of a silent no-op (the CNN zoo has no attention
        # to swap; ADVICE.md round 1 flagged exactly this failure mode
        # for ignored knobs).
        raise ValueError(
            f"model.attn_impl={model_cfg.attn_impl!r} only applies to "
            f"vit_sod, not {model_cfg.name!r}")
    if model_cfg.dlf_impl != "xla" and model_cfg.name != "hdfnet":
        raise ValueError(
            f"model.dlf_impl={model_cfg.dlf_impl!r} only applies to "
            f"hdfnet, not {model_cfg.name!r}")
    resample_impl = getattr(model_cfg, "resample_impl", "fast")
    _RESAMPLE_USERS = ("minet", "hdfnet", "gatenet", "u2net")
    if resample_impl != "fast" and model_cfg.name not in _RESAMPLE_USERS:
        # Loud instead of a silent no-op (same posture as attn_impl /
        # dlf_impl above): only the four decoder users of the
        # upsample+merge idiom route the knob.
        raise ValueError(
            f"model.resample_impl={resample_impl!r} only applies to "
            f"{_RESAMPLE_USERS}, not {model_cfg.name!r}")
    conv_impl = getattr(model_cfg, "conv_impl", "xla")
    if conv_impl != "xla" and model_cfg.name not in _RESAMPLE_USERS:
        # Same loudness for the conv-block seam: the four decoder
        # families (and their backbones) thread ConvBNAct's conv_impl;
        # elsewhere the knob would silently do nothing.
        raise ValueError(
            f"model.conv_impl={conv_impl!r} only applies to "
            f"{_RESAMPLE_USERS}, not {model_cfg.name!r}")
    dtype = jnp.dtype(model_cfg.compute_dtype)
    param_dtype = jnp.dtype(model_cfg.param_dtype)
    axis_name = "data" if model_cfg.sync_bn else None
    return _REGISTRY[model_cfg.name](
        model_cfg, dtype=dtype, param_dtype=param_dtype, axis_name=axis_name
    )


@register_model("minet")
def _build_minet(cfg, *, dtype, param_dtype, axis_name):
    from .minet import MINet

    return MINet(
        resample_impl=cfg.resample_impl,
        conv_impl=cfg.conv_impl,
        backbone=cfg.backbone,
        backbone_bn=cfg.backbone_bn,
        axis_name=axis_name,
        bn_momentum=cfg.bn_momentum,
        dtype=dtype,
        param_dtype=param_dtype,
    )


@register_model("u2net")
def _build_u2net(cfg, *, dtype, param_dtype, axis_name):
    from .u2net import U2Net

    if cfg.backbone not in ("none", "small"):
        raise ValueError(
            f"u2net is self-contained: backbone must be 'none' (full) or "
            f"'small' (U²-Net†), got {cfg.backbone!r}")
    return U2Net(
        resample_impl=cfg.resample_impl,
        conv_impl=cfg.conv_impl,
        small=cfg.backbone == "small",
        axis_name=axis_name,
        bn_momentum=cfg.bn_momentum,
        dtype=dtype,
        param_dtype=param_dtype,
    )


@register_model("basnet")
def _build_basnet(cfg, *, dtype, param_dtype, axis_name):
    from .basnet import BASNet

    return BASNet(
        axis_name=axis_name,
        bn_momentum=cfg.bn_momentum,
        dtype=dtype,
        param_dtype=param_dtype,
    )


@register_model("swin_sod")
def _build_swin_sod(cfg, *, dtype, param_dtype, axis_name):
    from .swin_sod import SwinSOD

    return SwinSOD(
        axis_name=axis_name,
        bn_momentum=cfg.bn_momentum,
        dtype=dtype,
        param_dtype=param_dtype,
    )


@register_model("gatenet")
def _build_gatenet(cfg, *, dtype, param_dtype, axis_name):
    from .gatenet import GateNet

    return GateNet(
        resample_impl=cfg.resample_impl,
        conv_impl=cfg.conv_impl,
        backbone=cfg.backbone,
        backbone_bn=cfg.backbone_bn,
        axis_name=axis_name,
        bn_momentum=cfg.bn_momentum,
        dtype=dtype,
        param_dtype=param_dtype,
    )


@register_model("vit_sod")
def _build_vit_sod(cfg, *, dtype, param_dtype, axis_name):
    from .vit_sod import PRESETS, ViTSOD

    if axis_name is not None:
        raise ValueError("vit_sod has no BatchNorm: set model.sync_bn=false")
    if cfg.backbone not in PRESETS:
        raise ValueError(
            f"vit_sod backbone must be one of {sorted(PRESETS)} "
            f"(encoder preset), got {cfg.backbone!r}")
    dim, depth, heads = PRESETS[cfg.backbone]
    return ViTSOD(dim=dim, depth=depth, heads=heads,
                  deep_supervision=cfg.deep_supervision,
                  attn_impl=cfg.attn_impl,
                  dtype=dtype, param_dtype=param_dtype)


@register_model("hdfnet")
def _build_hdfnet(cfg, *, dtype, param_dtype, axis_name):
    from .hdfnet import HDFNet

    return HDFNet(
        resample_impl=cfg.resample_impl,
        conv_impl=cfg.conv_impl,
        backbone=cfg.backbone,
        backbone_bn=cfg.backbone_bn,
        axis_name=axis_name,
        bn_momentum=cfg.bn_momentum,
        dlf_impl=cfg.dlf_impl,
        dtype=dtype,
        param_dtype=param_dtype,
    )
