"""MINet — Multi-scale Interactive Network for salient object detection.

TPU-native re-design of the MINet family (CVPR 2020; reference parity
target SURVEY.md §2 C5, call stack §3.3 — the reference mount was
unreadable, so the module structure follows the paper's description):

- backbone (VGG16 / ResNet50) → 5-level feature pyramid
- AIM (aggregate interaction): each level is fused with its resampled
  neighbours, so every decoder stage sees multi-scale context
- SIM (self-interaction): each decoder stage runs a two-resolution
  branch pair that exchanges information before merging
- head: single-channel saliency logit at input resolution

Framework conventions: NHWC, bf16 compute / f32 params, every model in
the zoo returns a *list* of logit maps at input resolution with element
0 the primary prediction (deep-supervision losses consume the list
uniformly; MINet has a single output).
"""

from __future__ import annotations

from typing import Any, List, Optional

import jax.numpy as jnp
from flax import linen as nn

from .backbones import ResNet50, VGG16
from .layers import (ConvBNAct, max_pool, resample_merge, resize_to,
                     upsample_like)


class SIM(nn.Module):
    """Self-interaction module: high-res / low-res branch exchange."""

    width: int
    axis_name: Optional[str] = None
    resample_impl: str = "fast"
    conv_impl: Optional[str] = None
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        kw = dict(axis_name=self.axis_name, conv_impl=self.conv_impl,
                  dtype=self.dtype, param_dtype=self.param_dtype)
        h = ConvBNAct(self.width, (3, 3), **kw)(x, train)
        l = max_pool(ConvBNAct(self.width // 2, (3, 3), **kw)(x, train))
        # Exchange: each branch receives the other, resampled (the
        # upsample+add / upsample+concat merges are the fused-resample
        # decoder idiom — model.resample_impl picks the strategy).
        h2 = ConvBNAct(self.width, (3, 3), **kw)(
            resample_merge(ConvBNAct(self.width, (3, 3), **kw)(l, train), h,
                           mode="add", impl=self.resample_impl),
            train,
        )
        l2 = ConvBNAct(self.width // 2, (3, 3), **kw)(
            l + max_pool(ConvBNAct(self.width // 2, (3, 3), **kw)(h, train)),
            train,
        )
        merged = resample_merge(l2, h2, mode="concat", x_first=False,
                                impl=self.resample_impl)
        return ConvBNAct(self.width, (3, 3), **kw)(merged, train)


class AIM(nn.Module):
    """Aggregate interaction: fuse a level with its resampled neighbours."""

    width: int
    axis_name: Optional[str] = None
    resample_impl: str = "fast"
    conv_impl: Optional[str] = None
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, below, cur, above, train: bool = False):
        kw = dict(axis_name=self.axis_name, conv_impl=self.conv_impl,
                  dtype=self.dtype, param_dtype=self.param_dtype)
        parts = [ConvBNAct(self.width, (3, 3), **kw)(cur, train)]
        if below is not None:  # finer level → downsample to cur's size
            b = ConvBNAct(self.width, (3, 3), **kw)(below, train)
            parts.append(resize_to(b, cur.shape[1:3],
                                   impl=self.resample_impl))
        if above is not None:  # coarser level → upsample to cur's size
            a = ConvBNAct(self.width, (3, 3), **kw)(above, train)
            parts.append(upsample_like(a, cur, impl=self.resample_impl))
        return ConvBNAct(self.width, (3, 3), **kw)(parts, train)


class MINet(nn.Module):
    backbone: str = "vgg16"
    backbone_bn: bool = True  # False → torchvision vgg16 layout for weight porting
    width: int = 64
    axis_name: Optional[str] = None
    bn_momentum: float = 0.9
    # Decoder resample strategy (model.resample_impl):
    # fast | xla | convt | fused — see layers.resample_merge.
    resample_impl: str = "fast"
    # Conv-block strategy (model.conv_impl): xla | fused — see
    # layers.ConvBNAct; threaded to every conv block, backbone included.
    conv_impl: Optional[str] = None
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, image, depth=None, *, train: bool = False) -> List[jnp.ndarray]:
        del depth  # RGB-only model; uniform zoo signature
        x = image.astype(self.dtype)
        bkw = dict(axis_name=self.axis_name, bn_momentum=self.bn_momentum,
                   conv_impl=self.conv_impl,
                   dtype=self.dtype, param_dtype=self.param_dtype)
        if self.backbone == "vgg16":
            feats = VGG16(use_bn=self.backbone_bn, **bkw)(x, train=train)
        elif self.backbone == "resnet50":
            feats = ResNet50(**bkw)(x, train=train)
        else:
            raise ValueError(f"MINet: unknown backbone {self.backbone!r}")

        kw = dict(axis_name=self.axis_name, conv_impl=self.conv_impl,
                  dtype=self.dtype, param_dtype=self.param_dtype)
        rkw = dict(resample_impl=self.resample_impl, **kw)

        # AIM per level.
        agg = []
        for i, f in enumerate(feats):
            below = feats[i - 1] if i > 0 else None
            above = feats[i + 1] if i < len(feats) - 1 else None
            agg.append(AIM(self.width, **rkw)(below, f, above, train=train))

        # Top-down decoder with SIM refinement.
        d = agg[-1]
        d = SIM(self.width, **rkw)(d, train=train)
        for i in range(len(agg) - 2, -1, -1):
            d = resample_merge(d, agg[i], mode="add",
                               impl=self.resample_impl)
            d = SIM(self.width, **rkw)(d, train=train)

        # Head → full-resolution single-channel logit.
        h = ConvBNAct(32, (3, 3), **kw)(d, train=train)
        logit = nn.Conv(1, (3, 3), padding="SAME", dtype=self.dtype,
                        param_dtype=self.param_dtype)(h)
        logit = resize_to(logit, image.shape[1:3],
                          impl=self.resample_impl).astype(jnp.float32)
        return [logit]
