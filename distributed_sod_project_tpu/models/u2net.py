"""U²-Net — nested U-structure with RSU blocks, 7-level deep supervision.

TPU-native re-design of U²-Net (Qin et al., PR 2020; reference parity
target SURVEY.md §2 C5 and config ``u2net_ds7`` [B:10] — the reference
mount was unreadable, so the topology follows the paper):

- encoder: RSU7→RSU6→RSU5→RSU4→RSU4F→RSU4F with 2× max-pool between
- decoder: mirror RSU stack on concatenated skip connections
- heads: one 1-channel side logit per decoder stage + bottleneck, all
  upsampled to input resolution, plus a fused logit from their concat
  → returns **7 logits**, element 0 the fused (primary) prediction.

TPU notes: every RSU's inner U-loop is a static Python loop over a
fixed depth, so the whole net traces to one static XLA graph; convs are
NHWC/bf16 on the MXU; the dilated RSU4F variant trades pooling for
dilation so the deepest stages keep spatial extent without dynamic
shapes.
"""

from __future__ import annotations

from typing import Any, List, Optional

import jax.numpy as jnp
from flax import linen as nn

from .layers import (ConvBNAct, max_pool, resample_merge, resize_to,
                     upsample_like)


class RSU(nn.Module):
    """Residual U-block: depth-``levels`` U-net with a residual skip."""

    levels: int  # e.g. 7 for RSU7
    mid: int
    out: int
    axis_name: Optional[str] = None
    bn_momentum: float = 0.9
    resample_impl: str = "fast"
    conv_impl: Optional[str] = None
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        kw = dict(axis_name=self.axis_name, bn_momentum=self.bn_momentum,
                  conv_impl=self.conv_impl,
                  dtype=self.dtype, param_dtype=self.param_dtype)
        xin = ConvBNAct(self.out, (3, 3), **kw)(x, train)

        # Contracting path: levels-1 encoder stages (pool between).
        enc = [ConvBNAct(self.mid, (3, 3), **kw)(xin, train)]
        for _ in range(self.levels - 2):
            enc.append(ConvBNAct(self.mid, (3, 3), **kw)(max_pool(enc[-1]), train))
        # Bottom: dilated conv at the coarsest resolution.
        d = ConvBNAct(self.mid, (3, 3), dilation=2, **kw)(enc[-1], train)
        # Expanding path: merge with skips, upsample back.
        for i in range(self.levels - 2, -1, -1):
            d = ConvBNAct(
                self.mid if i > 0 else self.out, (3, 3), **kw
            )([d, enc[i]], train)
            if i > 0:
                d = upsample_like(d, enc[i - 1], impl=self.resample_impl)
        return d + xin


class RSU4F(nn.Module):
    """Dilated RSU: fixed resolution, dilation 1/2/4/8 instead of pooling."""

    mid: int
    out: int
    axis_name: Optional[str] = None
    bn_momentum: float = 0.9
    conv_impl: Optional[str] = None
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        kw = dict(axis_name=self.axis_name, bn_momentum=self.bn_momentum,
                  conv_impl=self.conv_impl,
                  dtype=self.dtype, param_dtype=self.param_dtype)
        xin = ConvBNAct(self.out, (3, 3), **kw)(x, train)
        e1 = ConvBNAct(self.mid, (3, 3), dilation=1, **kw)(xin, train)
        e2 = ConvBNAct(self.mid, (3, 3), dilation=2, **kw)(e1, train)
        e3 = ConvBNAct(self.mid, (3, 3), dilation=4, **kw)(e2, train)
        b = ConvBNAct(self.mid, (3, 3), dilation=8, **kw)(e3, train)
        d3 = ConvBNAct(self.mid, (3, 3), dilation=4, **kw)(
            [b, e3], train)
        d2 = ConvBNAct(self.mid, (3, 3), dilation=2, **kw)(
            [d3, e2], train)
        d1 = ConvBNAct(self.out, (3, 3), dilation=1, **kw)(
            [d2, e1], train)
        return d1 + xin


class U2Net(nn.Module):
    """Full U²-Net.  ``small=True`` gives the U²-Net† (lite) widths."""

    small: bool = False
    axis_name: Optional[str] = None
    bn_momentum: float = 0.9
    # Decoder resample strategy (model.resample_impl):
    # fast | xla | convt | fused — see layers.resample_merge.
    resample_impl: str = "fast"
    # Conv-block strategy (model.conv_impl): xla | fused — see
    # layers.ConvBNAct; threaded to every RSU conv block.
    conv_impl: Optional[str] = None
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, image, depth=None, *, train: bool = False) -> List[jnp.ndarray]:
        del depth  # RGB-only model; uniform zoo signature
        x = image.astype(self.dtype)
        kw = dict(axis_name=self.axis_name, bn_momentum=self.bn_momentum,
                  conv_impl=self.conv_impl,
                  dtype=self.dtype, param_dtype=self.param_dtype)
        # RSU blocks resample internally; RSU4F is resolution-fixed.
        rkw = dict(resample_impl=self.resample_impl, **kw)
        if self.small:
            # U²-Net†: every stage 16/64.
            enc_spec = [(7, 16, 64), (6, 16, 64), (5, 16, 64), (4, 16, 64)]
            f_mid, f_out = 16, 64
            dec_spec = [(4, 16, 64), (5, 16, 64), (6, 16, 64), (7, 16, 64)]
        else:
            enc_spec = [(7, 32, 64), (6, 32, 128), (5, 64, 256), (4, 128, 512)]
            f_mid, f_out = 256, 512
            dec_spec = [(4, 128, 256), (5, 64, 128), (6, 32, 64), (7, 16, 64)]

        # Encoder: 4 RSU stages + 2 dilated stages, pooling between all 6.
        feats = []
        h = x
        for lv, mid, out in enc_spec:
            h = RSU(lv, mid, out, **rkw)(h, train)
            feats.append(h)
            h = max_pool(h)
        h = RSU4F(f_mid, f_out, **kw)(h, train)
        feats.append(h)
        h = max_pool(h)
        h = RSU4F(f_mid, f_out, **kw)(h, train)  # En_6 (bottleneck)

        # Decoder: RSU4F then the mirrored RSU stack on concat skips.
        sides = [h]  # bottleneck side output source
        d = RSU4F(f_mid, f_out, **kw)(
            resample_merge(h, feats[4], mode="concat",
                           impl=self.resample_impl), train)
        sides.append(d)
        for (lv, mid, out), skip in zip(dec_spec, feats[3::-1]):
            d = RSU(lv, mid, out, **rkw)(
                resample_merge(d, skip, mode="concat",
                               impl=self.resample_impl), train)
            sides.append(d)

        # Side heads: 3x3 conv → 1ch logit, upsampled to input resolution.
        hw = image.shape[1:3]
        logits = []
        for s in reversed(sides):  # finest (d1) first
            l = nn.Conv(1, (3, 3), padding="SAME", dtype=self.dtype,
                        param_dtype=self.param_dtype)(s)
            logits.append(resize_to(l, hw, impl=self.resample_impl)
                          .astype(jnp.float32))
        # Fused head over all 6 side logits.
        fused = nn.Conv(1, (1, 1), dtype=self.dtype,
                        param_dtype=self.param_dtype)(
            jnp.concatenate([l.astype(self.dtype) for l in logits], axis=-1))
        return [fused.astype(jnp.float32)] + logits
