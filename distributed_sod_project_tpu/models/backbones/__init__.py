from .vgg import VGG16
from .resnet import ResNet, ResNet34, ResNet50

__all__ = ["VGG16", "ResNet", "ResNet34", "ResNet50"]
