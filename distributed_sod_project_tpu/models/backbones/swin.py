"""Swin-T backbone — hierarchical window-attention feature pyramid.

Stretch config [B:11] (SURVEY.md §2 C6).  Swin-Tiny layout: patch-embed
4×4 → C=96, depths (2,2,6,2), heads (3,6,12,24), 2× patch-merging
between stages → pyramid at strides 4/8/16/32.

TPU-first design decisions:
- Window partition/reverse are pure reshapes/transposes of a statically
  padded NHWC tensor — no gather ops; the shifted variant is two
  ``jnp.roll``s (XLA lowers to concat-of-slices, cheap on TPU).
- Attention is one batched einsum over all windows at once:
  [B·nW, heads, win², head_dim] — a large MXU contraction instead of
  many small ones.
- Shifted-window masking uses the standard region-id trick computed
  from static window geometry at trace time.
- Tensor parallelism: the train step is shard_map-manual, so head
  sharding is expressed with explicit in_specs on a ``model`` axis by
  the TP step builder, not with boxed param metadata (which conflicts
  with manual mesh axes).  Heads-per-device stays an integer for every
  power-of-two ``model`` size up to the head count.
- Resolutions that need global (non-windowed) attention at pod scale
  route through ``parallel.ring_attention`` (the ``seq`` axis); at SOD
  resolutions windows fit on-chip and the ring is size 1.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

Dtype = Any


def window_partition(x: jnp.ndarray, w: int) -> jnp.ndarray:
    """[B,H,W,C] → [B·nW, w·w, C]; H,W must be multiples of w."""
    b, h, wd, c = x.shape
    x = x.reshape(b, h // w, w, wd // w, w, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(-1, w * w, c)


def window_reverse(x: jnp.ndarray, w: int, h: int, wd: int) -> jnp.ndarray:
    """Inverse of :func:`window_partition`."""
    b = x.shape[0] // ((h // w) * (wd // w))
    x = x.reshape(b, h // w, wd // w, w, w, -1)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(b, h, wd, -1)


def _relative_position_index(w: int) -> np.ndarray:
    """Static [w²,w²] index into the (2w-1)² relative-bias table."""
    coords = np.stack(np.meshgrid(np.arange(w), np.arange(w),
                                  indexing="ij")).reshape(2, -1)
    rel = coords[:, :, None] - coords[:, None, :]  # 2, w², w²
    rel = rel.transpose(1, 2, 0) + (w - 1)
    return (rel[..., 0] * (2 * w - 1) + rel[..., 1]).astype(np.int32)


def _shift_attn_mask(h: int, wd: int, w: int, shift: int) -> np.ndarray:
    """Static region-id mask for shifted windows: [nW, w², w²] bool
    (True = may attend)."""
    img = np.zeros((h, wd), np.int32)
    cnt = 0
    for hs in (slice(0, -w), slice(-w, -shift), slice(-shift, None)):
        for ws in (slice(0, -w), slice(-w, -shift), slice(-shift, None)):
            img[hs, ws] = cnt
            cnt += 1
    ids = window_partition(img[None, ..., None].astype(np.float32), w)
    ids = np.asarray(ids).squeeze(-1).astype(np.int32)  # [nW, w²]
    return ids[:, :, None] == ids[:, None, :]


class WindowAttention(nn.Module):
    dim: int
    heads: int
    window: int
    axis_name: Optional[str] = None  # unused (no BN); uniform ctor surface
    dtype: Dtype = jnp.float32
    param_dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x, mask=None):
        """x: [nB, w², C]; mask: [nW, w², w²] bool or None."""
        nb, n, c = x.shape
        hd = self.dim // self.heads
        dense_kw = dict(dtype=self.dtype, param_dtype=self.param_dtype)
        qkv = nn.Dense(self.dim * 3, use_bias=True,
                       kernel_init=nn.initializers.xavier_uniform(),
                       **dense_kw)(x)
        # HEAD-major packed columns — (heads, 3, hd), not the official
        # (3, heads, hd): a tensor-parallel column shard of the fused
        # kernel then lands on complete per-head (q,k,v) triples
        # whenever heads % model == 0, so the attention below needs no
        # GSPMD resharding (parallel/tp.py).  The weight porter permutes
        # official checkpoints into this order (_qkv_to_head_major).
        qkv = qkv.reshape(nb, n, self.heads, 3, hd).transpose(3, 0, 2, 1, 4)
        q, k, v = qkv[0], qkv[1], qkv[2]  # [nB, H, n, hd]

        bias_table = self.param(
            "rel_pos_bias", nn.initializers.truncated_normal(0.02),
            ((2 * self.window - 1) ** 2, self.heads), self.param_dtype)
        idx = _relative_position_index(self.window)
        bias = bias_table[idx.reshape(-1)].reshape(n, n, self.heads)
        bias = bias.transpose(2, 0, 1)[None]  # [1, H, n, n]

        s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                       preferred_element_type=jnp.float32)
        s = s / np.sqrt(hd) + bias.astype(jnp.float32)
        if mask is not None:
            nw = mask.shape[0]
            s = s.reshape(nb // nw, nw, self.heads, n, n)
            s = jnp.where(mask[None, :, None], s, -1e9)
            s = s.reshape(nb, self.heads, n, n)
        p = jax.nn.softmax(s, axis=-1).astype(self.dtype)
        out = jnp.einsum("bhqk,bhkd->bhqd", p, v)
        out = out.transpose(0, 2, 1, 3).reshape(nb, n, self.dim)
        return nn.Dense(self.dim,
                        kernel_init=nn.initializers.xavier_uniform(),
                        **dense_kw)(out)


class SwinBlock(nn.Module):
    dim: int
    heads: int
    window: int
    shift: int = 0
    mlp_ratio: float = 4.0
    dtype: Dtype = jnp.float32
    param_dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        """x: [B, H, W, C] with H,W already multiples of ``window``."""
        b, h, wd, c = x.shape
        ln_kw = dict(dtype=self.dtype, param_dtype=self.param_dtype)
        y = nn.LayerNorm(**ln_kw)(x)
        if self.shift:
            y = jnp.roll(y, (-self.shift, -self.shift), axis=(1, 2))
            mask = jnp.asarray(_shift_attn_mask(h, wd, self.window, self.shift))
        else:
            mask = None
        y = window_partition(y, self.window)
        y = WindowAttention(self.dim, self.heads, self.window,
                            dtype=self.dtype, param_dtype=self.param_dtype)(
            y, mask)
        y = window_reverse(y, self.window, h, wd)
        if self.shift:
            y = jnp.roll(y, (self.shift, self.shift), axis=(1, 2))
        x = x + y

        z = nn.LayerNorm(**ln_kw)(x)
        z = nn.Dense(int(self.dim * self.mlp_ratio), dtype=self.dtype,
                     param_dtype=self.param_dtype)(z)
        # Exact (erf) GELU: the official Swin checkpoints were trained
        # with torch nn.GELU, and the tanh approximation would add a
        # systematic error to ported weights (tools/port_torch_weights).
        z = nn.gelu(z, approximate=False)
        z = nn.Dense(self.dim, dtype=self.dtype,
                     param_dtype=self.param_dtype)(z)
        return x + z


class SwinT(nn.Module):
    """Swin-Tiny; returns a 4-level pyramid (strides 4/8/16/32)."""

    embed_dim: int = 96
    depths: Sequence[int] = (2, 2, 6, 2)
    heads: Sequence[int] = (3, 6, 12, 24)
    window: int = 7
    axis_name: Optional[str] = None  # no BN; kept for zoo ctor parity
    bn_momentum: float = 0.9        # idem
    dtype: Dtype = jnp.float32
    param_dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False) -> List[jnp.ndarray]:
        del train  # no dropout/BN in this deployment
        x = x.astype(self.dtype)
        x = nn.Conv(self.embed_dim, (4, 4), strides=(4, 4), padding="VALID",
                    dtype=self.dtype, param_dtype=self.param_dtype)(x)
        x = nn.LayerNorm(dtype=self.dtype, param_dtype=self.param_dtype)(x)

        feats: List[jnp.ndarray] = []
        dim = self.embed_dim
        for stage, (depth, heads) in enumerate(zip(self.depths, self.heads)):
            if stage:
                # Patch merging: 2×2 neighbourhood concat → linear to 2C.
                b, h, wd, c = x.shape
                x = x[:, : h - h % 2, : wd - wd % 2]
                x = jnp.concatenate(
                    [x[:, 0::2, 0::2], x[:, 1::2, 0::2],
                     x[:, 0::2, 1::2], x[:, 1::2, 1::2]], axis=-1)
                x = nn.LayerNorm(dtype=self.dtype,
                                 param_dtype=self.param_dtype)(x)
                dim *= 2
                x = nn.Dense(dim, use_bias=False, dtype=self.dtype,
                             param_dtype=self.param_dtype)(x)

            # Pad to window multiples (static — shapes known at trace).
            b, h, wd, c = x.shape
            w = min(self.window, h, wd)
            ph = (-h) % w
            pw = (-wd) % w
            if ph or pw:
                x = jnp.pad(x, ((0, 0), (0, ph), (0, pw), (0, 0)))
            for i in range(depth):
                shift = w // 2 if (i % 2 and min(x.shape[1:3]) > w) else 0
                x = SwinBlock(dim, heads, w, shift=shift, dtype=self.dtype,
                              param_dtype=self.param_dtype)(x)
            x = x[:, :h, :wd]
            feats.append(
                nn.LayerNorm(dtype=self.dtype,
                             param_dtype=self.param_dtype)(x))
        return feats
