"""ResNet-34/50 feature-pyramid backbones (SURVEY.md §2 C6).

Returns a 5-level pyramid: stem conv output (stride 2) plus the four
residual stages (strides 4/8/16/32).  For 320×320 input the spatial
sizes are 160/80/40/20/10; channels 64/256/512/1024/2048 for R50
(bottleneck ×4 expansion) and 64/64/128/256/512 for R34 (basic blocks).

Design notes (TPU):
- NHWC everywhere; the stem's 7×7/2 conv and all 3×3s tile cleanly onto
  the MXU in bf16.
- Identity shortcuts use strided 1×1 projections exactly where the
  channel/stride changes, matching the torchvision graph so ImageNet
  weights port 1:1 (``tools/port_torch_weights.py``).
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple

import jax.numpy as jnp
from flax import linen as nn

from ..layers import ConvBNAct

_S2D_FALLBACK_WARNED: set = set()


def _warn_s2d_fallback(shape: Tuple[int, ...]) -> None:
    """One warning per input shape per process (the module is traced
    under jit — a plain print would fire once per trace anyway, but
    dedup keeps multi-config sweeps readable)."""
    key = tuple(shape[1:3])
    if key in _S2D_FALLBACK_WARNED:
        return
    _S2D_FALLBACK_WARNED.add(key)
    from ...utils import get_logger

    get_logger().warning(
        "DSOD_STEM_IMPL=s2d requested but input H×W %s is odd — "
        "falling back to the plain 7x7 stem.  Any benchmark tagged "
        "stem=s2d at this size measured the PLAIN stem.", key)


class BasicBlock(nn.Module):
    features: int
    strides: int = 1
    axis_name: Optional[str] = None
    bn_momentum: float = 0.9
    conv_impl: Optional[str] = None
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        kw = dict(
            axis_name=self.axis_name,
            bn_momentum=self.bn_momentum,
            conv_impl=self.conv_impl,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
        )
        residual = x
        y = ConvBNAct(self.features, (3, 3), strides=self.strides, **kw)(x, train)
        y = ConvBNAct(self.features, (3, 3), act=None, **kw)(y, train)
        if residual.shape[-1] != self.features or self.strides != 1:
            residual = ConvBNAct(
                self.features, (1, 1), strides=self.strides, act=None, **kw
            )(x, train)
        return nn.relu(y + residual)


class Bottleneck(nn.Module):
    features: int  # bottleneck width; output is 4× this
    strides: int = 1
    axis_name: Optional[str] = None
    bn_momentum: float = 0.9
    conv_impl: Optional[str] = None
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        kw = dict(
            axis_name=self.axis_name,
            bn_momentum=self.bn_momentum,
            conv_impl=self.conv_impl,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
        )
        out_ch = self.features * 4
        residual = x
        y = ConvBNAct(self.features, (1, 1), **kw)(x, train)
        y = ConvBNAct(self.features, (3, 3), strides=self.strides, **kw)(y, train)
        y = ConvBNAct(out_ch, (1, 1), act=None, **kw)(y, train)
        if residual.shape[-1] != out_ch or self.strides != 1:
            residual = ConvBNAct(
                out_ch, (1, 1), strides=self.strides, act=None, **kw
            )(x, train)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block: type = Bottleneck
    axis_name: Optional[str] = None
    bn_momentum: float = 0.9
    conv_impl: Optional[str] = None
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False) -> List[jnp.ndarray]:
        kw = dict(
            axis_name=self.axis_name,
            bn_momentum=self.bn_momentum,
            conv_impl=self.conv_impl,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
        )
        feats: List[jnp.ndarray] = []
        # DSOD_STEM_IMPL=s2d: compute the stem as space-to-depth + 4×4
        # conv (layers.SpaceToDepthStem) — same arithmetic, same param
        # tree, TPU-friendlier tiling.  Env-knob A/B like
        # DSOD_RESIZE_IMPL (bench.py keys baselines on it).
        from ...utils import envvars

        if envvars.read("DSOD_STEM_IMPL") == "s2d":
            if x.shape[1] % 2 == 0 and x.shape[2] % 2 == 0:
                from ..layers import SpaceToDepthStem

                skw = {k: v for k, v in kw.items() if k != "conv_impl"}
                x = SpaceToDepthStem(64, name="ConvBNAct_0", **skw)(x, train)
            else:
                # ADVICE r3: odd H or W forces the plain-stem fallback,
                # but bench.py tags the baseline key with the env var —
                # a silent fallback would record numbers labeled s2d
                # that actually ran the 7x7 stem.  Warn loudly so a
                # mislabeled A/B leg is visible in its log.
                _warn_s2d_fallback(x.shape)
                x = ConvBNAct(64, (7, 7), strides=2, **kw)(x, train)
        else:
            x = ConvBNAct(64, (7, 7), strides=2, **kw)(x, train)
        feats.append(x)  # stride 2
        # padding (1,1), not SAME: matches torch MaxPool2d(3,2,1) so
        # ported ImageNet weights see the alignment they trained with.
        x = nn.max_pool(x, (3, 3), strides=(2, 2),
                        padding=((1, 1), (1, 1)))
        widths = (64, 128, 256, 512)
        for stage, (n_blocks, width) in enumerate(zip(self.stage_sizes, widths)):
            for i in range(n_blocks):
                strides = 2 if (i == 0 and stage > 0) else 1
                x = self.block(width, strides=strides, **kw)(x, train)
            feats.append(x)  # strides 4, 8, 16, 32
        return feats


def ResNet50(**kw) -> ResNet:
    return ResNet(stage_sizes=(3, 4, 6, 3), block=Bottleneck, **kw)


def ResNet34(**kw) -> ResNet:
    return ResNet(stage_sizes=(3, 4, 6, 3), block=BasicBlock, **kw)
