"""VGG16 feature-pyramid backbone (SURVEY.md §2 C6).

Returns the 5-level pyramid SOD decoders consume: the last conv of each
VGG stage, at strides 1/2/4/8/16 relative to the input (for 320×320
input: 320, 160, 80, 40, 20).  Channels: 64/128/256/512/512.

``use_bn=False`` reproduces the classic torchvision ``vgg16`` layout
(what MINet-class models load ImageNet weights for);  ``use_bn=True``
is the ``vgg16_bn`` layout and the better from-scratch default.  Both
are supported by ``tools/port_torch_weights.py``.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence

import jax.numpy as jnp
from flax import linen as nn

from ..layers import ConvBNAct, max_pool

# Convs per stage and channel widths of VGG16.
_STAGES: Sequence[int] = (2, 2, 3, 3, 3)
_WIDTHS: Sequence[int] = (64, 128, 256, 512, 512)


class VGG16(nn.Module):
    use_bn: bool = True
    axis_name: Optional[str] = None
    bn_momentum: float = 0.9
    conv_impl: Optional[str] = None
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False) -> List[jnp.ndarray]:
        feats: List[jnp.ndarray] = []
        for stage, (n_convs, width) in enumerate(zip(_STAGES, _WIDTHS)):
            if stage > 0:
                x = max_pool(x)
            for _ in range(n_convs):
                x = ConvBNAct(
                    width,
                    use_bn=self.use_bn,
                    axis_name=self.axis_name,
                    bn_momentum=self.bn_momentum,
                    conv_impl=self.conv_impl,
                    dtype=self.dtype,
                    param_dtype=self.param_dtype,
                )(x, train=train)
            feats.append(x)
        return feats
