"""HDFNet — hierarchical dynamic filtering for RGB-D SOD.

TPU-native re-design of HDFNet (Pang et al., ECCV 2020; reference
parity target SURVEY.md §2 C5 and the RGB-D config [B:9] — reference
mount unreadable, topology per the paper):

- two encoder streams: RGB and depth (depth replicated to 3 channels),
  sharing the backbone architecture but not parameters
- hierarchical dynamic filtering at the three deepest levels: the depth
  stream *generates* spatially-variant kernels that filter the fused
  RGB+depth features (region-adaptive receptive fields)
- top-down decoder over the filtered pyramid; deep supervision with a
  side head per decoder level.

Returns **3 logits** at input resolution, element 0 primary.

TPU notes: dynamic filtering is the classic "local conv" op that is a
scatter/gather nightmare on GPUs; here it is expressed as
``conv_general_dilated_patches`` (an im2col XLA lowers to cheap
reshapes/slices) followed by an einsum over the patch axis — a large
batched contraction the MXU eats directly, with multi-dilation sharing
one patch extraction per dilation rate.
"""

from __future__ import annotations

from typing import Any, List, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from .backbones import ResNet50, VGG16
from .layers import ConvBNAct, resample_merge, resize_to


def dynamic_local_filter(x: jnp.ndarray, kernels: jnp.ndarray, ksize: int,
                         dilation: int = 1,
                         impl: str = "xla") -> jnp.ndarray:
    """Apply per-position ``ksize×ksize`` depthwise kernels to ``x``.

    x: (B,H,W,C); kernels: (B,H,W,ksize*ksize) — one kernel per spatial
    location, shared across channels (HDFNet's kernel-generation units
    emit channel-shared spatial kernels).

    ``impl='pallas'`` routes through the fused VMEM kernel
    (``pallas/dynamic_filter.py``) — same math, no ksize²-wide im2col
    materialisation in HBM.
    """
    if impl == "pallas":
        from ..pallas.dynamic_filter import fused_dynamic_filter

        return fused_dynamic_filter(x, kernels, ksize, dilation)
    if impl != "xla":
        raise ValueError(f"impl must be 'xla' or 'pallas', got {impl!r}")
    b, h, w, c = x.shape
    # im2col: (B,H,W, C*ksize*ksize) with channel-major ordering.
    patches = jax.lax.conv_general_dilated_patches(
        x, (ksize, ksize), window_strides=(1, 1), padding="SAME",
        rhs_dilation=(dilation, dilation),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    patches = patches.reshape(b, h, w, c, ksize * ksize)
    return jnp.einsum("bhwck,bhwk->bhwc", patches,
                      kernels.astype(patches.dtype))


class KernelGenUnit(nn.Module):
    """Generate normalized per-position kernels from guidance features."""

    ksize: int = 3
    axis_name: Optional[str] = None
    bn_momentum: float = 0.9
    conv_impl: Optional[str] = None
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, g, train: bool = False):
        kw = dict(axis_name=self.axis_name, bn_momentum=self.bn_momentum,
                  conv_impl=self.conv_impl,
                  dtype=self.dtype, param_dtype=self.param_dtype)
        k = ConvBNAct(64, (3, 3), **kw)(g, train)
        k = nn.Conv(self.ksize * self.ksize, (3, 3), padding="SAME",
                    dtype=self.dtype, param_dtype=self.param_dtype)(k)
        # Softmax over the patch axis → kernels are convex weights, which
        # keeps the filtered activations bounded (bf16-safe).
        return jax.nn.softmax(k.astype(jnp.float32), axis=-1)


class DDPM(nn.Module):
    """Dense dynamic pyramid module: multi-dilation dynamic filtering."""

    width: int
    dilations: tuple = (1, 2, 4)
    axis_name: Optional[str] = None
    bn_momentum: float = 0.9
    dlf_impl: str = "xla"
    conv_impl: Optional[str] = None
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, fused, guide, train: bool = False):
        kw = dict(axis_name=self.axis_name, bn_momentum=self.bn_momentum,
                  conv_impl=self.conv_impl,
                  dtype=self.dtype, param_dtype=self.param_dtype)
        x = ConvBNAct(self.width, (3, 3), **kw)(fused, train)
        outs = [x]
        for rate in self.dilations:
            kern = KernelGenUnit(axis_name=self.axis_name,
                                 bn_momentum=self.bn_momentum,
                                 conv_impl=self.conv_impl,
                                 dtype=self.dtype,
                                 param_dtype=self.param_dtype)(guide, train)
            outs.append(dynamic_local_filter(x, kern, ksize=3, dilation=rate,
                                             impl=self.dlf_impl))
        return ConvBNAct(self.width, (3, 3), **kw)(outs, train)


class HDFNet(nn.Module):
    backbone: str = "vgg16"
    backbone_bn: bool = True
    width: int = 64
    axis_name: Optional[str] = None
    bn_momentum: float = 0.9
    dlf_impl: str = "xla"  # xla (im2col+einsum) | pallas (fused VMEM)
    # Decoder resample strategy (model.resample_impl):
    # fast | xla | convt | fused — see layers.resample_merge.
    resample_impl: str = "fast"
    # Conv-block strategy (model.conv_impl): xla | fused — see
    # layers.ConvBNAct; threaded to every conv block, both backbones
    # included.
    conv_impl: Optional[str] = None
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    def _backbone(self, name_suffix: str):
        bkw = dict(axis_name=self.axis_name, bn_momentum=self.bn_momentum,
                   conv_impl=self.conv_impl,
                   dtype=self.dtype, param_dtype=self.param_dtype)
        if self.backbone == "vgg16":
            return VGG16(use_bn=self.backbone_bn, name=f"vgg_{name_suffix}", **bkw)
        if self.backbone == "resnet50":
            return ResNet50(name=f"resnet_{name_suffix}", **bkw)
        raise ValueError(f"HDFNet: unknown backbone {self.backbone!r}")

    @nn.compact
    def __call__(self, image, depth, *, train: bool = False) -> List[jnp.ndarray]:
        if depth is None:
            raise ValueError("HDFNet is an RGB-D model: `depth` is required "
                             "(data cfg use_depth=True, SURVEY.md §2 C7)")
        x = image.astype(self.dtype)
        d = depth.astype(self.dtype)
        if d.shape[-1] == 1:
            d = jnp.repeat(d, 3, axis=-1)

        rgb_feats = self._backbone("rgb")(x, train=train)
        dep_feats = self._backbone("depth")(d, train=train)

        kw = dict(axis_name=self.axis_name, bn_momentum=self.bn_momentum,
                  conv_impl=self.conv_impl,
                  dtype=self.dtype, param_dtype=self.param_dtype)

        # Fuse the three deepest levels with dynamic filtering; the depth
        # stream is the kernel-generating guide (hierarchical: each level
        # gets its own DDPM).
        filtered = []
        for lvl in (2, 3, 4):
            # The two streams convolve as their channel concat inside
            # DDPM's entry conv — the ConvBNAct seam fuses it away on
            # the fused arm.
            fused = [rgb_feats[lvl], dep_feats[lvl]]
            guide = ConvBNAct(self.width, (3, 3), **kw)(dep_feats[lvl], train)
            filtered.append(DDPM(self.width, axis_name=self.axis_name,
                                 bn_momentum=self.bn_momentum,
                                 dlf_impl=self.dlf_impl,
                                 conv_impl=self.conv_impl,
                                 dtype=self.dtype,
                                 param_dtype=self.param_dtype)(
                fused, guide, train))

        # Top-down decoder: deepest filtered level down to the finest two
        # RGB levels (compressed to `width`).
        dec = filtered[-1]
        sides = []  # supervised decoder states, coarse → fine
        for skip in (filtered[1], filtered[0]):
            dec = resample_merge(dec, skip, mode="add",
                                 impl=self.resample_impl)
            dec = ConvBNAct(self.width, (3, 3), **kw)(dec, train)
            sides.append(dec)
        for lvl in (1, 0):
            skip = ConvBNAct(self.width, (3, 3), **kw)(rgb_feats[lvl], train)
            dec = resample_merge(dec, skip, mode="add",
                                 impl=self.resample_impl)
            dec = ConvBNAct(self.width, (3, 3), **kw)(dec, train)

        hw = image.shape[1:3]
        logits = []
        # Primary head on the finest decoder state + one deep-supervision
        # head per intermediate decoder level.
        for s in (dec, sides[1], sides[0]):
            l = nn.Conv(1, (3, 3), padding="SAME", dtype=self.dtype,
                        param_dtype=self.param_dtype)(s)
            logits.append(resize_to(l, hw, impl=self.resample_impl)
                          .astype(jnp.float32))
        return logits
