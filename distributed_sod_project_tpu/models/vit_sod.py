"""ViT-SOD — the long-context zoo member (SURVEY.md §5 "long-context").

A plain-ViT encoder with GLOBAL attention over every patch token and a
per-token unpatchify head.  Unlike the CNN zoo (and Swin's windowed
attention), its attention cost grows quadratically with resolution —
this is the model whose training genuinely needs sequence parallelism,
and its architecture is chosen so SP is EXACT:

- ``patchify`` is a stride-``patch`` convolution with kernel ==
  stride: patches are disjoint tiles, so a block of patch ROWS of the
  image maps to a block of tokens with no cross-device halo.
- LayerNorm / MLP / the linear unpatchify head are per-token.
- Attention is the ONLY cross-token op; under sequence parallelism it
  is computed exactly by ``parallel.ring_attention`` (K/V blocks on a
  ``lax.ppermute`` ring), injected via the ``attn_fn`` call argument.
- No BatchNorm → no cross-replica stat plumbing in the SP step.

So the whole forward/backward decomposes over token blocks: each
``seq`` device runs this module on its slice of image rows with
``pos_row_offset`` pointing into the shared positional table
(``parallel/sp.py`` builds that step).  Run on the full image with the
default ``attn_fn`` (single-device softmax), the math is identical —
eval/test/predict need no special casing.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional

import jax.numpy as jnp
import numpy as np
from flax import linen as nn

class _Block(nn.Module):
    """Pre-LN transformer block; attention core injected per call."""

    dim: int
    heads: int
    mlp_ratio: int
    dtype: Any
    param_dtype: Any

    @nn.compact
    def __call__(self, x, attn_fn: Callable, *, train: bool):
        b, n, d = x.shape
        h = self.heads
        kw = dict(dtype=self.dtype, param_dtype=self.param_dtype)

        y = nn.LayerNorm(dtype=jnp.float32, param_dtype=self.param_dtype)(x)
        # Separate q/k/v projections (not one fused 3d Dense): the TP
        # rules column-shard each (d, d) kernel, and with heads % model
        # == 0 the shard boundary lands on a head boundary — a fused
        # kernel's packed 3d axis would split mid-k/v and force GSPMD
        # to re-gather qkv every block (parallel/tp.py VIT_TP_RULES).
        q = nn.Dense(d, name="q", **kw)(y)
        k = nn.Dense(d, name="k", **kw)(y)
        v = nn.Dense(d, name="v", **kw)(y)
        # [B, N, D] -> heads-major [B, H, N, D/H] (ring_attention layout).
        def split_heads(t):
            return t.reshape(b, n, h, d // h).transpose(0, 2, 1, 3)

        out = attn_fn(split_heads(q), split_heads(k), split_heads(v))
        out = out.transpose(0, 2, 1, 3).reshape(b, n, d)
        x = x + nn.Dense(d, name="proj", **kw)(out)

        y = nn.LayerNorm(dtype=jnp.float32, param_dtype=self.param_dtype)(x)
        y = nn.Dense(self.mlp_ratio * d, name="mlp_up", **kw)(y)
        # Exact (erf) GELU — what timm/DeiT checkpoints were trained
        # with; the tanh approximation costs ~1e-3 per activation,
        # which compounds over ported 12-block encoders.
        y = nn.gelu(y, approximate=False)
        x = x + nn.Dense(d, name="mlp_down", **kw)(y)
        return x


class ViTSOD(nn.Module):
    """Global-attention SOD.  Returns ``[logit]`` ([B,H,W,1], f32).

    ``full_grid``: the FULL image's (patch_rows, patch_cols).  Defaults
    to this call's image — pass it when the image argument is a row
    SLICE of a larger image (sequence parallelism), together with
    ``pos_row_offset`` (this slice's first patch row, may be traced)
    and an ``attn_fn`` that performs global attention across devices.
    """

    patch: int = 16
    dim: int = 384
    depth: int = 8
    heads: int = 6
    mlp_ratio: int = 4
    deep_supervision: bool = True  # aux unpatchify head at mid-depth
    # Default attention core when no attn_fn is injected: "xla" is the
    # materialized-scores softmax (full_attention), "flash" the Pallas
    # tiled kernel (pallas/flash_attention.py) — same math, O(N·D) HBM
    # instead of O(N²), which is what makes high-resolution single-chip
    # training/eval fit.  An explicit attn_fn (the SP ring) always wins.
    attn_impl: str = "xla"
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, image, depth=None, *, train: bool = False,
                 attn_fn: Optional[Callable] = None,
                 full_grid: Optional[tuple] = None,
                 pos_row_offset=0) -> List[jnp.ndarray]:
        del depth  # RGB-only member; uniform zoo signature
        if attn_fn is None:
            from ..parallel.ring_attention import resolve_attn_fn

            attn_fn = resolve_attn_fn(self.attn_impl)
        x = image.astype(self.dtype)
        b, hh, ww, _ = x.shape
        p = self.patch
        if hh % p or ww % p:
            raise ValueError(f"image {hh}x{ww} not divisible by patch {p}")
        rows, cols = hh // p, ww // p
        grid = tuple(full_grid) if full_grid is not None else (rows, cols)

        # Disjoint-tile patchify: kernel == stride == patch.
        x = nn.Conv(self.dim, (p, p), strides=(p, p), dtype=self.dtype,
                    param_dtype=self.param_dtype, name="patch_embed")(x)
        x = x.reshape(b, rows * cols, self.dim)

        pos = self.param(
            "pos_embed",
            nn.initializers.truncated_normal(0.02),
            (grid[0] * grid[1], self.dim), self.param_dtype)
        # This call's token window of the full positional table: row
        # offset may be a traced per-device index (SP), so slice
        # dynamically; cols always span the full width.
        start = jnp.asarray(pos_row_offset, jnp.int32) * grid[1]
        from jax import lax

        pos_win = lax.dynamic_slice_in_dim(pos, start, rows * cols, axis=0)
        x = x + pos_win[None].astype(self.dtype)

        def unpatchify_head(tokens, name):
            """Per-token D -> p*p logits, tiled back to pixels — the
            only head shape that keeps the model halo-free for SP."""
            y = nn.LayerNorm(dtype=jnp.float32,
                             param_dtype=self.param_dtype,
                             name=f"{name}_norm")(tokens)
            l = nn.Dense(p * p, dtype=jnp.float32,
                         param_dtype=self.param_dtype, name=name)(y)
            l = l.reshape(b, rows, cols, p, p)
            return l.transpose(0, 1, 3, 2, 4).reshape(b, hh, ww, 1
                                                      ).astype(jnp.float32)

        aux = None
        for i in range(self.depth):
            x = _Block(dim=self.dim, heads=self.heads,
                       mlp_ratio=self.mlp_ratio, dtype=self.dtype,
                       param_dtype=self.param_dtype, name=f"block{i}")(
                           x, attn_fn, train=train)
            if self.deep_supervision and i == self.depth // 2 - 1:
                aux = unpatchify_head(x, "aux_head")

        logits = [unpatchify_head(x, "head")]
        if aux is not None:
            logits.append(aux)
        return logits


PRESETS = {
    # name: (dim, depth, heads).  "small"/"base" match the public
    # ViT-S/16 and ViT-B/16 shapes so timm/DeiT ImageNet checkpoints
    # port directly (tools/port_torch_weights.py --arch vit); "none"
    # stays a lighter from-scratch baseline that keeps the 320px
    # quadratic-attention model comfortably on one chip.
    "none": (384, 8, 6),
    "small": (384, 12, 6),
    "base": (768, 12, 12),
    # Debug/CI variant: compiles in seconds on one CPU — the model for
    # engine-plumbing smokes where the architecture is irrelevant.
    "tiny": (32, 2, 2),
}
