from .registry import build_model, list_models, register_model

__all__ = ["build_model", "list_models", "register_model"]
