"""Merge ported ImageNet backbone weights into zoo param trees.

The port tool (tools/port_torch_weights.py) emits backbone-level
{params, batch_stats} trees.  Models embed the backbone at different
scopes (``VGG16_0`` in MINet, ``vgg_rgb``/``vgg_depth`` in HDFNet, …),
so the loader finds every subtree that *structurally matches* the
ported tree — same child names and leaf shapes — and swaps it in
(HDFNet: both streams get the same ImageNet init, the standard RGB-D
practice of initialising the depth stream from the RGB weights).
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def save_npz(path: str, params: Dict, stats: Dict,
             meta: Dict[str, str] | None = None) -> None:
    """Flatten {params, batch_stats} into an npz with /-joined keys
    (the interchange format tools/port_torch_weights.py writes).
    ``meta`` string pairs ride along under ``meta/`` keys — layout
    markers (e.g. the Swin qkv column order) that loaders use to
    reject stale ports whose shapes still match."""
    flat: Dict[str, np.ndarray] = {}

    def walk(prefix, tree, out):
        for k, v in tree.items():
            if isinstance(v, dict):
                walk(f"{prefix}{k}/", v, out)
            else:
                out[f"{prefix}{k}"] = np.asarray(v)

    walk("params/", params, flat)
    walk("batch_stats/", stats, flat)
    for k, v in (meta or {}).items():
        flat[f"meta/{k}"] = np.asarray(str(v))
    np.savez(path, **flat)


def load_npz_meta(path: str) -> Dict[str, str]:
    """The ``meta/`` string pairs of an npz (empty for older files)."""
    flat = np.load(path)
    return {k[len("meta/"):]: str(flat[k])
            for k in flat.files if k.startswith("meta/")}


def load_npz(path: str) -> Tuple[Dict, Dict]:
    """Inverse of :func:`save_npz`."""
    flat = np.load(path)
    params: Dict = {}
    stats: Dict = {}
    for key in flat.files:
        parts = key.split("/")
        if parts[0] == "meta":
            continue  # string markers, not weights (load_npz_meta)
        root = params if parts[0] == "params" else stats
        node = root
        for p in parts[1:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = flat[key]
    return params, stats


def _bias_table_windows(shape) -> int:
    """(2w-1)² rows → w, or 0 when the shape is not a bias table."""
    if len(shape) != 2:
        return 0
    side = int(round(shape[0] ** 0.5))
    if side * side != shape[0] or side % 2 != 1:
        return 0
    return (side + 1) // 2


def _adaptable_bias(key: str, target_shape, ported_shape) -> bool:
    """Swin relative-position bias tables adapt across window sizes by
    bicubic resize (the standard fine-tune-at-new-resolution practice):
    [(2w-1)², H] ↔ [(2w'-1)², H]."""
    return (key == "rel_pos_bias"
            and len(target_shape) == 2 and len(ported_shape) == 2
            and target_shape[1] == ported_shape[1]
            and _bias_table_windows(target_shape) > 0
            and _bias_table_windows(ported_shape) > 0)


def _resize_bias_table(v: np.ndarray, target_shape) -> np.ndarray:
    from scipy import ndimage

    side_src = int(round(v.shape[0] ** 0.5))
    side_tgt = int(round(target_shape[0] ** 0.5))
    grid = np.asarray(v, np.float32).reshape(side_src, side_src, -1)
    zoom = (side_tgt / side_src, side_tgt / side_src, 1.0)
    out = ndimage.zoom(grid, zoom, order=3)
    return out.reshape(side_tgt * side_tgt, -1)


def _is_prefix_match(subtree: Dict, ported: Dict) -> bool:
    """ported's keys are a subset-by-name with equal (or bias-table
    adaptable) leaf shapes."""
    for k, v in ported.items():
        if k not in subtree:
            return False
        if isinstance(v, dict):
            if not isinstance(subtree[k], dict) or not _is_prefix_match(
                    subtree[k], v):
                return False
        else:
            tgt = subtree[k]
            if isinstance(tgt, dict):
                return False
            if tuple(np.shape(tgt)) != tuple(v.shape) and not \
                    _adaptable_bias(k, np.shape(tgt), v.shape):
                return False
    return True


def _merge(subtree: Dict, ported: Dict) -> Dict:
    out = dict(subtree)
    for k, v in ported.items():
        if isinstance(v, dict):
            out[k] = _merge(subtree[k], v)
        else:
            tgt = jnp.asarray(subtree[k])
            if tuple(tgt.shape) != tuple(v.shape):
                v = _resize_bias_table(np.asarray(v), tgt.shape)
            out[k] = jnp.asarray(v, tgt.dtype)
    return out


def _find_and_merge(tree: Dict, ported: Dict, path="") -> Tuple[Dict, List[str]]:
    if _is_prefix_match(tree, ported):
        return _merge(tree, ported), [path or "/"]
    hits: List[str] = []
    out = dict(tree)
    for k, v in tree.items():
        if isinstance(v, dict):
            merged, sub_hits = _find_and_merge(v, ported, f"{path}/{k}")
            if sub_hits:
                out[k] = merged
                hits.extend(sub_hits)
    return out, hits


def _check_qkv_layout(npz_path: str, p_params) -> None:
    """Reject Swin ports whose fused-qkv columns predate the head-major
    packing: shapes are unchanged, so a stale file would load cleanly
    and silently scramble q/k/v inside every attention."""
    def has_window_attn(tree) -> bool:
        if not isinstance(tree, dict):
            return False
        return any(k.startswith("WindowAttention") or has_window_attn(v)
                   for k, v in tree.items())

    if not has_window_attn(p_params):
        return
    if load_npz_meta(npz_path).get("qkv_layout") != "head_major":
        raise ValueError(
            f"{npz_path}: Swin port predates the head-major qkv column "
            "packing (no meta/qkv_layout=head_major marker) — its "
            "shapes still match, but q/k/v would be scrambled inside "
            "every attention.  Re-port the checkpoint with the current "
            "tools/port_torch_weights.py")


def load_pretrained(variables: Dict[str, Any], npz_path: str) -> Dict[str, Any]:
    """Return ``variables`` with every matching backbone subtree replaced
    by the ported weights from ``npz_path``.  Raises if nothing matches
    (a silently ignored checkpoint is the worst failure mode)."""
    p_params, p_stats = load_npz(npz_path)
    _check_qkv_layout(npz_path, p_params)
    new_params, hits = _find_and_merge(variables["params"], p_params)
    if not hits:
        raise ValueError(
            f"{npz_path}: no subtree of the model's params matches the "
            "ported backbone (wrong --arch or wrong model?)")
    out = dict(variables)
    out["params"] = new_params
    if p_stats and "batch_stats" in variables:
        new_stats, s_hits = _find_and_merge(variables["batch_stats"], p_stats)
        if s_hits:
            out["batch_stats"] = new_stats
    from ..utils.logging import get_logger

    get_logger().info("loaded pretrained backbone %s into %s",
                      npz_path, ", ".join(hits))
    return out
