"""BASNet — boundary-aware SOD: predict module + residual refinement.

TPU-native re-design of BASNet (Qin et al., CVPR 2019; reference parity
target SURVEY.md §2 C5, deep-supervision config [B:10] — reference mount
unreadable, topology per the paper):

- predict module: ResNet34-style encoder kept at full input resolution
  through stage 1 (3×3/1 stem, no pooling), two extra 512-wide stages
  past the backbone, a dilated bridge, and a mirrored decoder with a
  side head at every depth
- refine module (RRM): a small full-resolution encoder–decoder whose
  output is a *residual* added to the coarse saliency logit

Returns **8 logits**: element 0 the refined prediction, element 1 the
coarse predict-module output, then the deeper side outputs — all at
input resolution so ``deep_supervision_loss`` consumes them uniformly.

TPU notes: the encoder is pure 3×3 convs (MXU-friendly); the refinement
residual is elementwise and fuses into the surrounding graph; all
resizes are static-shape ``jax.image.resize``.
"""

from __future__ import annotations

from typing import Any, List, Optional

import jax.numpy as jnp
from flax import linen as nn

from .backbones.resnet import BasicBlock
from .layers import ConvBNAct, max_pool, resize_to, upsample_like


class _DecoderStage(nn.Module):
    """Three ConvBNActs on the concat of the upsampled path and the skip."""

    width: int
    axis_name: Optional[str] = None
    bn_momentum: float = 0.9
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, d, skip, train: bool = False):
        kw = dict(axis_name=self.axis_name, bn_momentum=self.bn_momentum,
                  dtype=self.dtype, param_dtype=self.param_dtype)
        x = jnp.concatenate([upsample_like(d, skip), skip], axis=-1)
        for _ in range(3):
            x = ConvBNAct(self.width, (3, 3), **kw)(x, train)
        return x


class RefineModule(nn.Module):
    """RRM: 4-level encoder–decoder producing a residual logit."""

    width: int = 64
    axis_name: Optional[str] = None
    bn_momentum: float = 0.9
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, logit, train: bool = False):
        kw = dict(axis_name=self.axis_name, bn_momentum=self.bn_momentum,
                  dtype=self.dtype, param_dtype=self.param_dtype)
        x = ConvBNAct(self.width, (3, 3), **kw)(logit.astype(self.dtype), train)
        skips = []
        for _ in range(4):
            x = ConvBNAct(self.width, (3, 3), **kw)(x, train)
            skips.append(x)
            x = max_pool(x)
        x = ConvBNAct(self.width, (3, 3), **kw)(x, train)
        for skip in reversed(skips):
            x = ConvBNAct(self.width, (3, 3), **kw)(
                jnp.concatenate([upsample_like(x, skip), skip], axis=-1), train)
        res = nn.Conv(1, (3, 3), padding="SAME", dtype=self.dtype,
                      param_dtype=self.param_dtype)(x)
        return logit + res.astype(jnp.float32)


class BASNet(nn.Module):
    axis_name: Optional[str] = None
    bn_momentum: float = 0.9
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, image, depth=None, *, train: bool = False) -> List[jnp.ndarray]:
        del depth  # RGB-only model; uniform zoo signature
        x = image.astype(self.dtype)
        kw = dict(axis_name=self.axis_name, bn_momentum=self.bn_momentum,
                  dtype=self.dtype, param_dtype=self.param_dtype)

        # --- predict-module encoder ---------------------------------
        # Stem at full resolution (3×3/1 — BASNet keeps stage 1 unpooled).
        x = ConvBNAct(64, (3, 3), **kw)(x, train)
        feats = []
        stage_blocks = [(3, 64, 1), (4, 128, 2), (6, 256, 2), (3, 512, 2)]
        for n, width, first_stride in stage_blocks:
            for i in range(n):
                x = BasicBlock(width, strides=first_stride if i == 0 else 1,
                               **kw)(x, train)
            feats.append(x)  # strides 1, 2, 4, 8
        for _ in range(2):  # extra stages → strides 16, 32
            x = max_pool(x)
            for _ in range(3):
                x = BasicBlock(512, **kw)(x, train)
            feats.append(x)

        # Bridge: dilated 512 convs at the coarsest resolution.
        b = x
        for _ in range(3):
            b = ConvBNAct(512, (3, 3), dilation=2, **kw)(b, train)

        # --- decoder with side heads --------------------------------
        widths = [512, 512, 512, 256, 128, 64]
        d = b
        stages = [b]
        for width, skip in zip(widths, reversed(feats)):
            d = _DecoderStage(width, **kw)(d, skip, train)
            stages.append(d)

        hw = image.shape[1:3]
        side_logits = []
        for s in reversed(stages):  # finest decoder stage first, bridge last
            l = nn.Conv(1, (3, 3), padding="SAME", dtype=self.dtype,
                        param_dtype=self.param_dtype)(s)
            side_logits.append(resize_to(l, hw).astype(jnp.float32))

        refined = RefineModule(axis_name=self.axis_name,
                               bn_momentum=self.bn_momentum, dtype=self.dtype,
                               param_dtype=self.param_dtype)(
            side_logits[0], train)
        return [refined] + side_logits
