"""Pallas fused conv-stage kernels — conv + BN + ReLU in one VMEM pass.

The round-4 roofline reconciliation (docs/PERFORMANCE.md) put 72% of
the measured flagship step in convolution fusions, with the fine
160/80-px buckets running 3.3x/2.1x off streaming bandwidth, and the
round-5 resample work pre-committed the verdict: "if the A/B lands at
~2%, the buckets' overhead lives inside the conv fusions themselves and
the next lever is a conv-stage kernel".  This module is that kernel
(ROADMAP item 4): the dominant encoder/decoder block of the zoo —
``ConvBNAct`` = 3x3/1x1 stride-1 conv -> BatchNorm -> ReLU — and its
decoder-head sibling conv(concat(parts)) run as ONE VMEM-resident pass
per image: inputs are read from HBM once, the concat operand is never
materialized, and the BN normalize + ReLU epilogue rides the conv's
VMEM tile instead of a second HBM round trip.

In-kernel form (the CPU-bitwise contraction): zero-pad the image tile
in VMEM, then for each static row-chunk build the im2col block
``(rows*w, kh*kw*cin)`` by concatenating the kh*kw shifted tap slices
(parts interleaved per tap in concat order) and run ONE
``jnp.dot(..., preferred_element_type=f32)`` against the reshaped
``(kh*kw*cin, cout)`` weight matrix.  Per output element this is the
SAME flattened (u, v, cin) contraction XLA:CPU's conv performs, so the
interpret-mode forward matches ``lax.conv_general_dilated`` BITWISE in
f32 (asserted, not assumed: tests/test_pallas_conv.py; below 9 output
pixels per image XLA switches its small-GEMM kernel and parity is f32
round-off instead) — the tap-by-tap
accumulation an earlier draft used differs at ~1e-5 (k*k partial sums
re-associate the reduction) and was rejected for exactly that reason.
The row chunking only bounds VMEM (im2col is 9x the input bytes for a
3x3); rows are independent, so chunked == unchunked bitwise.

Epilogues, replicated op-for-op from the XLA arm so parity is bitwise
(f32) / MXU-native (bf16) rather than merely close:

- ``none``  — conv only (the train-mode arm: batch-statistics BN needs
  the whole batch, so ``ConvBNAct`` keeps flax's BatchNorm after the
  kernel when ``train=True``);
- ``bias``  — ``+ bias`` in compute dtype (``use_bn=False`` sites,
  nn.Conv's own order);
- ``bn``    — inference-mode BatchNorm folded: ``(c - mean) * mul +
  beta`` with ``mul = rsqrt(var + eps) * scale`` computed OUTSIDE the
  kernel in flax's exact op order (``_normalize``: subtract first,
  then the combined multiplier — NOT the algebraic ``c*s + o`` fold,
  which re-rounds differently);

each optionally followed by an in-kernel ``max(y, 0)`` (= jax.nn.relu's
value; its grad-at-0 convention is matched in the VJP via ``y > 0``).

Precision arms (PR 6 composition): the weight operand may be an int8 /
fp8 **quantized** leaf from ``serve/precision.py`` — the kernel casts
it to the compute dtype in-VMEM (|q| <= 127 and e4m3 values are exact
in bf16) and the per-output-channel dequant scale folds into the
epilogue as one row multiply, so quantized weights ship to the MXU at
1/4 the HBM bytes with NO dense dequantized copy in HBM.  Quantized
calls are serve-only and non-differentiable (loud error).

Backward is closed-form, not a recompute: ``dx`` is the SAME conv
kernel applied to the cotangent with the spatially-flipped,
io-transposed weights (stride-1 same-conv transpose identity), and
``dw`` is a second accumulate-over-grid kernel doing one
``(cin, h*w) x (h*w, cout)`` contraction per tap.  The cheap epilogue
adjoints (relu mask, BN vector grads) run as plain XLA elementwise +
reductions outside the kernels.  The inference-mode BN fold needs the
pre-epilogue conv output ``c`` for d(mul); the fwd-for-vjp variant
emits it as a second output — the plain forward (no grad requested)
never pays that write.

Like the other kernels here: one image per grid step, f32-element VMEM
budget checked by the CALLER (``layers.ConvBNAct``) via
:func:`fused_conv_available` with per-site fallback, scoped-VMEM
ceiling via the shared v2/v3 denylist rule (pallas/vmem_budget.py,
``DSOD_CONV_VMEM_MB`` override), ``interpret`` auto (interpret on CPU,
Mosaic on TPU), exactness + the Mosaic lowering guarded in
tests/test_pallas_conv.py via ``jax.export(platforms=['tpu'])``.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# f32-element budget for ONE grid step's working set: raw input block +
# zero-padded VMEM copy + one im2col row-chunk + output tile + weights.
# 12M elems ~= 48 MB f32 against the 100 MB scoped-VMEM ceiling — sized
# so every flagship DECODER site (AIM/SIM 160px x 64ch ~= 5.7M) and the
# deepest fine backbone stage (VGG stage-2 @160px x 128ch ~= 9.8M) fit,
# while the 320px encoder stages (~16M+) fall back to the XLA arm by
# design (same posture as fused_resample's U²-Net full-width exclusion).
_MAX_TILE_ELEMS = 12 * 1024 * 1024

# Static rows per im2col chunk: 8 rows x 160 cols x 576 taps ~= 0.74M
# f32 elems at the flagship decoder shape — the im2col blowup (kh*kw x
# the input bytes) stays a bounded slice of the budget.
_CHUNK_ROWS = 8

# Fixed operand order for the epilogue vectors (pallas positional refs).
_VEC_ORDER = ("qscale", "mean", "mul", "bias")


def is_quantized_weight(w) -> bool:
    """True when ``w`` is a serve-precision quantized leaf (int8/fp8)
    the kernel dequantizes in-VMEM (scale folded into the epilogue).
    The dtype set is serve/precision.py's one definition."""
    from ..serve.precision import quant_dtypes

    return jnp.asarray(w).dtype in quant_dtypes()


def _compiler_params():
    """Scoped-VMEM ceiling via the shared v2/v3 small-VMEM denylist
    rule (pallas/vmem_budget.py); ``DSOD_CONV_VMEM_MB`` overrides
    either way (0 = compiler default)."""
    from .vmem_budget import scoped_vmem_params

    return scoped_vmem_params("DSOD_CONV_VMEM_MB")


def _interpret(interpret):
    return jax.default_backend() == "cpu" if interpret is None else interpret


class _Spec(NamedTuple):
    """Static kernel configuration (hashable: custom_vjp nondiff arg)."""

    kh: int
    kw: int
    dilation: int
    splits: Tuple[int, ...]  # per-part channel widths, concat order
    mode: str                # none | bias | bn
    relu: bool
    vec_names: Tuple[str, ...]
    interpret: bool


def fused_conv_available(part_shapes: Sequence[Tuple[int, ...]],
                         kernel: Tuple[int, int], dilation: int,
                         features: int) -> bool:
    """True when one grid step's tiles fit the f32-element VMEM budget.
    Callers fall back to the XLA path otherwise (same numerics, no
    fusion).  Static shape constraints (stride 1, odd kernel) are the
    caller's gate — this prices only the memory envelope."""
    kh, kw = kernel
    _, h, w, _ = part_shapes[0]
    cin = sum(int(s[-1]) for s in part_shapes)
    ph, pw = dilation * (kh // 2), dilation * (kw // 2)
    taps = kh * kw * cin
    elems = h * w * cin                       # raw input block(s)
    elems += (h + 2 * ph) * (w + 2 * pw) * cin  # zero-padded VMEM copy
    elems += min(_CHUNK_ROWS, h) * w * taps   # im2col row chunk
    elems += h * w * features                 # output tile
    elems += taps * features                  # weight matrix
    return elems <= _MAX_TILE_ELEMS


def _zero_pad2(x, ph: int, pw: int):
    """Zero-pad a (h, w, c) tile spatially — concatenate form, so the
    padded copy lives only in VMEM (jnp.pad is avoided for the same
    reason fused_resample's _clamp_pad is value-level)."""
    if ph:
        zr = jnp.zeros((ph,) + x.shape[1:], x.dtype)
        x = jnp.concatenate([zr, x, zr], axis=0)
    if pw:
        zc = jnp.zeros((x.shape[0], pw, x.shape[2]), x.dtype)
        x = jnp.concatenate([zc, x, zc], axis=1)
    return x


def _epilogue(acc, spec: _Spec, vecs: Dict[str, Any], cd):
    """The f32 conv accumulator -> the block's output, replicating the
    XLA arm's op/dtype order exactly (module docstring)."""
    if "qscale" in vecs:
        acc = acc * vecs["qscale"]  # (rows, w, cout) * (1, cout), f32
    c = acc.astype(cd)              # nn.Conv's output dtype
    if spec.mode == "bias":
        y = c + vecs["bias"]        # bias pre-cast to cd (nn.Conv order)
    elif spec.mode == "bn":
        # flax _normalize: subtract, then the combined multiplier, then
        # beta — all promoting to f32 against the f32 stats — then the
        # cast back to the compute dtype.
        y = ((c - vecs["mean"]) * vecs["mul"] + vecs["bias"]).astype(cd)
    else:
        y = c
    if spec.relu:
        y = jnp.maximum(y, jnp.zeros((), y.dtype))
    return y, c


def _fwd_kernel(*refs, spec: _Spec, cd, save_preact: bool):
    n = len(spec.splits)
    part_refs = refs[:n]
    w_ref = refs[n]
    vec_refs = dict(zip(spec.vec_names, refs[n + 1:n + 1 + len(spec.vec_names)]))
    out_refs = refs[n + 1 + len(spec.vec_names):]
    o_ref = out_refs[0]
    c_ref = out_refs[1] if save_preact else None

    kh, kw, d = spec.kh, spec.kw, spec.dilation
    ph, pw = d * (kh // 2), d * (kw // 2)
    h, w = o_ref.shape[1], o_ref.shape[2]
    cout = o_ref.shape[3]
    cin = sum(spec.splits)
    taps = kh * kw * cin

    xps = [_zero_pad2(r[0].astype(cd), ph, pw) for r in part_refs]
    wm = w_ref[...].astype(cd).reshape(taps, cout)
    vecs = {k: v[...] for k, v in vec_refs.items()}

    chunk = min(_CHUNK_ROWS, h)
    for s in range(0, h, chunk):
        rows = min(chunk, h - s)
        # im2col over the chunk: per tap (u, v), the parts' shifted
        # slices in concat order — the flattened (u, v, cin) contraction
        # index matches w.reshape(kh*kw*cin, cout) row-major exactly.
        slabs = []
        for u in range(kh):
            for v in range(kw):
                for xp in xps:
                    slabs.append(xp[s + u * d:s + u * d + rows,
                                    v * d:v * d + w, :])
        cols = jnp.concatenate(slabs, axis=-1) if len(slabs) > 1 \
            else slabs[0]
        acc = jnp.dot(cols.reshape(rows * w, taps), wm,
                      preferred_element_type=jnp.float32)
        acc = acc.reshape(rows, w, cout)
        y, c = _epilogue(acc, spec, vecs, cd)
        o_ref[0, s:s + rows] = y.astype(o_ref.dtype)
        if c_ref is not None:
            c_ref[0, s:s + rows] = c.astype(c_ref.dtype)


def _dw_kernel(*refs, spec: _Spec, cd):
    n = len(spec.splits)
    part_refs = refs[:n]
    g_ref = refs[n]
    o_ref = refs[n + 1]

    @pl.when(pl.program_id(0) == 0)
    def _init():  # noqa: ANN202 — pallas pattern
        o_ref[...] = jnp.zeros_like(o_ref)

    kh, kw, d = spec.kh, spec.kw, spec.dilation
    ph, pw = d * (kh // 2), d * (kw // 2)
    h, w, cout = g_ref.shape[1], g_ref.shape[2], g_ref.shape[3]

    xps = [_zero_pad2(r[0].astype(cd), ph, pw) for r in part_refs]
    g2 = g_ref[0].astype(cd).reshape(h * w, cout)
    for u in range(kh):
        for v in range(kw):
            slabs = [xp[u * d:u * d + h, v * d:v * d + w, :] for xp in xps]
            lhs = jnp.concatenate(slabs, axis=-1) if len(slabs) > 1 \
                else slabs[0]
            lhs = lhs.reshape(h * w, lhs.shape[-1])
            acc = jax.lax.dot_general(
                lhs, g2, dimension_numbers=(((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            o_ref[u, v] += acc.astype(o_ref.dtype)


def _img_spec(shape):
    n = len(shape)
    return pl.BlockSpec((1,) + tuple(shape),
                        lambda i, _n=n: (i,) + (0,) * _n)


def _full_spec(shape):
    n = len(shape)
    return pl.BlockSpec(tuple(shape), lambda i, _n=n: (0,) * _n)


def _vec2d(v):
    """Epilogue vector -> (1, C) so the VMEM ref is rank-2."""
    return jnp.asarray(v).reshape(1, -1)


def _call_fwd(parts, w, vecs: Dict[str, Any], spec: _Spec,
              save_preact: bool = False):
    b, h, wd, _ = parts[0].shape
    cd = parts[0].dtype
    cout = w.shape[-1]
    cin = sum(spec.splits)
    taps = spec.kh * spec.kw * cin
    vec_args = [_vec2d(vecs[k]) for k in spec.vec_names]
    out_shape = [jax.ShapeDtypeStruct((b, h, wd, cout), cd)]
    out_specs = [_img_spec((h, wd, cout))]
    if save_preact:
        out_shape.append(jax.ShapeDtypeStruct((b, h, wd, cout), cd))
        out_specs.append(_img_spec((h, wd, cout)))
    out = pl.pallas_call(
        partial(_fwd_kernel, spec=spec, cd=cd, save_preact=save_preact),
        grid=(b,),
        in_specs=[_img_spec(p.shape[1:]) for p in parts]
        + [_full_spec(w.shape)]
        + [_full_spec(v.shape) for v in vec_args],
        out_specs=out_specs if save_preact else out_specs[0],
        out_shape=out_shape if save_preact else out_shape[0],
        cost_estimate=pl.CostEstimate(
            flops=2.0 * b * h * wd * cout * taps, transcendentals=0,
            bytes_accessed=float(
                sum(p.size * p.dtype.itemsize for p in parts)
                + w.size * w.dtype.itemsize
                + (2 if save_preact else 1) * b * h * wd * cout
                * jnp.dtype(cd).itemsize)),
        interpret=spec.interpret,
        compiler_params=_compiler_params(),
    )(*parts, w, *vec_args)
    return out


def _call_dw(parts, g, spec: _Spec):
    b, h, wd, cout = g.shape
    cd = parts[0].dtype
    cin = sum(spec.splits)
    return pl.pallas_call(
        partial(_dw_kernel, spec=spec, cd=cd),
        grid=(b,),
        in_specs=[_img_spec(p.shape[1:]) for p in parts]
        + [_img_spec((h, wd, cout))],
        out_specs=_full_spec((spec.kh, spec.kw, cin, cout)),
        out_shape=jax.ShapeDtypeStruct(
            (spec.kh, spec.kw, cin, cout), jnp.float32),
        cost_estimate=pl.CostEstimate(
            flops=2.0 * b * h * wd * cout * spec.kh * spec.kw * cin,
            transcendentals=0,
            bytes_accessed=float(
                sum(p.size * p.dtype.itemsize for p in parts)
                + g.size * g.dtype.itemsize
                + 4 * spec.kh * spec.kw * cin * cout)),
        interpret=spec.interpret,
        compiler_params=_compiler_params(),
    )(*parts, g)


def _flip_transpose(w):
    """Stride-1 same-conv transpose weights: spatial flip + io swap."""
    return w[::-1, ::-1].transpose(0, 1, 3, 2)


@partial(jax.custom_vjp, nondiff_argnums=(3,))
def _fused_conv_diff(parts, w, vecs, spec: _Spec):
    return _call_fwd(parts, w, vecs, spec)


def _fused_conv_fwd(parts, w, vecs, spec: _Spec):
    if spec.mode == "bn":
        y, c = _call_fwd(parts, w, vecs, spec, save_preact=True)
    else:
        y, c = _call_fwd(parts, w, vecs, spec), None
    return y, (parts, w, vecs, y, c)


def _fused_conv_bwd(spec: _Spec, res, g):
    parts, w, vecs, y, c = res
    if "qscale" in vecs:
        raise NotImplementedError(
            "quantized fused-conv weights are a serve-only view; "
            "differentiate the dense arm instead")
    cd = parts[0].dtype
    dz = jnp.where(y > 0, g, jnp.zeros((), g.dtype)) if spec.relu else g
    dvecs = {}
    if spec.mode == "bn":
        dz32 = dz.astype(jnp.float32)
        axes = (0, 1, 2)
        # Cotangents must land on the PRIMAL dtypes: beta is a
        # param_dtype leaf (bf16 under bf16 params), mean/mul are f32
        # (BN stats / the f32-promoted fold product).
        dvecs["bias"] = jnp.sum(dz32, axes).astype(vecs["bias"].dtype)
        y0 = c.astype(jnp.float32) - vecs["mean"]
        dvecs["mul"] = jnp.sum(dz32 * y0, axes).astype(
            vecs["mul"].dtype)
        dy0 = dz32 * vecs["mul"]
        dvecs["mean"] = -jnp.sum(dy0, axes).astype(vecs["mean"].dtype)
        dc = dy0.astype(cd)
    elif spec.mode == "bias":
        dvecs["bias"] = jnp.sum(dz.astype(jnp.float32), (0, 1, 2)
                                ).astype(vecs["bias"].dtype)
        dc = dz
    else:
        dc = dz
    # dx: the transposed same-conv — the SAME forward kernel on the
    # cotangent with flipped/io-swapped weights, epilogue 'none'.
    bwd_spec = _Spec(spec.kh, spec.kw, spec.dilation,
                     (w.shape[-1],), "none", False, (), spec.interpret)
    dx = _call_fwd((dc.astype(cd),), _flip_transpose(w), {}, bwd_spec)
    dparts = []
    lo = 0
    for cw in spec.splits:
        dparts.append(dx[..., lo:lo + cw])
        lo += cw
    dw = _call_dw(parts, dc.astype(cd), spec).astype(w.dtype)
    return tuple(dparts), dw, dvecs


_fused_conv_diff.defvjp(_fused_conv_fwd, _fused_conv_bwd)


def fused_conv(parts, w, vecs: Optional[Dict[str, Any]] = None, *,
               kernel: Tuple[int, int], dilation: int = 1,
               mode: str = "none", relu: bool = False,
               interpret: Optional[bool] = None):
    """Fused conv(+concat)(+affine)(+ReLU) over NHWC ``parts``.

    ``parts`` is a sequence of same-spatial NHWC tensors convolved as
    their channel concatenation (one part = the plain conv; more = the
    decoder-head conv+concat, the concat never materialized in HBM).
    ``w`` is the ``(kh, kw, cin_total, cout)`` kernel in the compute
    dtype, or a serve-precision int8/fp8 quantized leaf (then
    ``vecs['qscale']`` must carry the per-output-channel dequant scale
    and the call is non-differentiable).  ``mode``/``relu`` select the
    epilogue (module docstring); ``vecs`` carries its f32 vectors
    (``mean``/``mul``/``bias``) or the cd-cast conv ``bias``.

    Shape/VMEM gating is the CALLER's job (``fused_conv_available`` /
    ``layers.ConvBNAct``) — this raises on malformed operands rather
    than silently falling back.
    """
    parts = tuple(jnp.asarray(p) for p in parts)
    if not parts or any(p.ndim != 4 for p in parts):
        raise ValueError(
            f"expected NHWC parts, got {[getattr(p, 'shape', p) for p in parts]}")
    sp = parts[0].shape[:3]
    if any(p.shape[:3] != sp for p in parts):
        raise ValueError(
            f"parts disagree on batch/spatial dims: "
            f"{[p.shape for p in parts]}")
    kh, kw = kernel
    if kh % 2 == 0 or kw % 2 == 0:
        raise ValueError(f"fused conv needs odd kernels, got {kernel}")
    cin = sum(p.shape[-1] for p in parts)
    if w.ndim != 4 or w.shape[:3] != (kh, kw, cin):
        raise ValueError(
            f"weight {w.shape} does not match kernel {kernel} x "
            f"cin {cin}")
    if mode not in ("none", "bias", "bn"):
        raise ValueError(f"mode must be none|bias|bn, got {mode!r}")
    vecs = dict(vecs or {})
    quant = is_quantized_weight(w)
    if quant and "qscale" not in vecs:
        raise ValueError("quantized weights need vecs['qscale']")
    names = tuple(k for k in _VEC_ORDER if k in vecs)
    if set(names) != set(vecs):
        raise ValueError(
            f"unknown epilogue vec(s) {sorted(set(vecs) - set(names))}")
    spec = _Spec(kh, kw, int(dilation),
                 tuple(int(p.shape[-1]) for p in parts), mode, bool(relu),
                 names, _interpret(interpret))
    if quant:
        # Serve-only fast path: no VJP (pallas has no autodiff rule, so
        # an accidental grad fails loudly rather than silently wrong).
        return _call_fwd(parts, w, vecs, spec)
    return _fused_conv_diff(parts, w, vecs, spec)
