"""Pallas fused SSIM (SURVEY.md §2.2; the 11×11-window loss of the
BASNet-style hybrid, losses/ssim.py).

The XLA path blurs a 5-moment channel stack with separable depthwise
convs — one HBM round trip for the stacked maps per level, times 7–8
deep-supervision levels.  This kernel computes the whole per-image SSIM
in VMEM: each grid step loads one image pair, builds the Gaussian blur
as BANDED MATRICES (blur-along-W = ``m @ K_w``, blur-along-H =
``K_h @ m`` — MXU contractions instead of VPU window sweeps; the taps
are symmetric so each band matrix is its own transpose), evaluates the
SSIM map pointwise, and writes back a single per-image sum.  HBM
traffic is exactly: read a, read b, write one scalar row.

Backward is a second kernel, not a recompute-in-XLA fallback: it
rebuilds the blurred moments, gets the pointwise partials via an
in-kernel ``jax.vjp`` (traces to elementwise ops — Mosaic-friendly),
and blurs them back through the same symmetric band matrices:

    dSum/da = G⊛∂S/∂μ_a + 2a ⊙ (G⊛∂S/∂E[a²]) + b ⊙ (G⊛∂S/∂E[ab])

Numerical parity with ``losses.ssim`` (forward AND gradients) is
asserted in tests/test_pallas_ssim.py; the real-TPU Mosaic lowering is
guarded by a ``jax.export(platforms=['tpu'])`` test (no chip needed).
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

_C1 = 0.01**2
_C2 = 0.03**2
_LANES = 128
_MAX_PIXELS = 448 * 448  # VMEM guard: beyond this, fall back to XLA


def _taps(window: int, sigma: float) -> np.ndarray:
    if window % 2 == 0:
        # The analytic backward relies on the band matrix being its own
        # transpose, which only holds for symmetric (odd-window) taps —
        # an even window would silently mirror the gradients.  The XLA
        # path (losses/ssim.py) handles even windows.
        raise ValueError(f"fused SSIM needs an odd window, got {window}")
    x = np.arange(window, dtype=np.float64) - window // 2
    g = np.exp(-(x**2) / (2.0 * sigma**2))
    return (g / g.sum()).astype(np.float32)


def _band(n: int, taps: np.ndarray):
    """(n, n) banded blur matrix K[i, j] = taps[j - i + r] — symmetric
    (symmetric taps), zero outside the band == 'SAME' zero padding."""
    r = len(taps) // 2
    i = lax.broadcasted_iota(jnp.int32, (n, n), 0)
    j = lax.broadcasted_iota(jnp.int32, (n, n), 1)
    diff = j - i
    k = jnp.zeros((n, n), jnp.float32)
    for t in range(len(taps)):
        k = k + jnp.where(diff == t - r, jnp.float32(taps[t]), 0.0)
    return k


def _blur_with(kh, kw, m):
    """K_h @ m @ K_w, both contractions in f32 on the MXU."""
    m = jnp.dot(kh, m, preferred_element_type=jnp.float32)
    return jnp.dot(m, kw, preferred_element_type=jnp.float32)


def _pointwise_ssim(mu_a, mu_b, e_aa, e_bb, e_ab):
    mu_aa, mu_bb, mu_ab = mu_a * mu_a, mu_b * mu_b, mu_a * mu_b
    var_a = e_aa - mu_aa
    var_b = e_bb - mu_bb
    cov = e_ab - mu_ab
    num = (2.0 * mu_ab + _C1) * (2.0 * cov + _C2)
    den = (mu_aa + mu_bb + _C1) * (var_a + var_b + _C2)
    return num / den


def _moments(a, b, kh, kw):
    return (_blur_with(kh, kw, a), _blur_with(kh, kw, b),
            _blur_with(kh, kw, a * a), _blur_with(kh, kw, b * b),
            _blur_with(kh, kw, a * b))


def _fwd_kernel(a_ref, b_ref, out_ref, *, taps):
    a = a_ref[0].astype(jnp.float32)
    b = b_ref[0].astype(jnp.float32)
    h, w = a.shape
    kh, kw = _band(h, taps), _band(w, taps)
    s = _pointwise_ssim(*_moments(a, b, kh, kw))
    lane = lax.broadcasted_iota(jnp.int32, (1, 1, _LANES), 2)
    out_ref[:] = jnp.where(lane == 0, jnp.sum(s), 0.0)


def _bwd_kernel(a_ref, b_ref, ga_ref, gb_ref, *, taps):
    a = a_ref[0].astype(jnp.float32)
    b = b_ref[0].astype(jnp.float32)
    h, w = a.shape
    kh, kw = _band(h, taps), _band(w, taps)

    def sum_from_moments(mu_a, mu_b, e_aa, e_bb, e_ab):
        return jnp.sum(_pointwise_ssim(mu_a, mu_b, e_aa, e_bb, e_ab))

    moms = _moments(a, b, kh, kw)
    _, vjp = jax.vjp(sum_from_moments, *moms)
    d_mu_a, d_mu_b, d_eaa, d_ebb, d_eab = vjp(jnp.float32(1.0))
    # Transpose of each blur is the same symmetric band matrix pair.
    g_eab = _blur_with(kh, kw, d_eab)
    ga = (_blur_with(kh, kw, d_mu_a) + 2.0 * a * _blur_with(kh, kw, d_eaa)
          + b * g_eab)
    gb = (_blur_with(kh, kw, d_mu_b) + 2.0 * b * _blur_with(kh, kw, d_ebb)
          + a * g_eab)
    ga_ref[:] = ga[None]
    gb_ref[:] = gb[None]


def _shape3(x) -> Tuple[int, int, int]:
    if x.ndim == 4:
        if x.shape[-1] != 1:
            raise ValueError(f"fused SSIM is single-channel, got {x.shape}")
        return x.shape[0], x.shape[1], x.shape[2]
    if x.ndim == 3:
        return x.shape
    raise ValueError(f"expected [B,H,W,1] or [B,H,W], got {x.shape}")


def fused_ssim_available(shape) -> bool:
    """The kernel holds one image pair + moments in VMEM; multi-channel
    or very large maps must use the XLA path."""
    shape = tuple(shape)
    if len(shape) == 4 and shape[-1] != 1:
        return False
    if len(shape) not in (3, 4):
        return False
    return shape[1] * shape[2] <= _MAX_PIXELS


@partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def fused_ssim_mean(a, b, window: int = 11, sigma: float = 1.5):
    """mean SSIM(a, b) — identical to ``losses.ssim.ssim`` for
    single-channel maps, one Pallas pass per image."""
    val, _ = _ssim_fwd(a, b, window, sigma)
    return val


def _run(kernel, a, b, out_shapes, taps, interpret=None):
    from jax.experimental import pallas as pl

    bsz, h, w = _shape3(a)
    a3 = a.reshape(bsz, h, w)
    b3 = b.reshape(bsz, h, w)
    if h * w > _MAX_PIXELS:
        raise ValueError(
            f"image {h}x{w} exceeds the fused-SSIM VMEM budget "
            f"({_MAX_PIXELS} px) — use losses.ssim (XLA) instead")
    return pl.pallas_call(
        partial(kernel, taps=taps),
        grid=(bsz,),
        in_specs=[pl.BlockSpec((1, h, w), lambda i: (i, 0, 0)),
                  pl.BlockSpec((1, h, w), lambda i: (i, 0, 0))],
        out_specs=[pl.BlockSpec((1,) + o, lambda i: (i,) + (0,) * len(o))
                   for o in out_shapes],
        out_shape=[jax.ShapeDtypeStruct((bsz,) + o, jnp.float32)
                   for o in out_shapes],
        interpret=(jax.default_backend() == "cpu"
                   if interpret is None else interpret),
    )(a3, b3)


def _ssim_fwd(a, b, window, sigma):
    bsz, h, w = _shape3(a)
    taps = _taps(window, sigma)
    (out,) = _run(_fwd_kernel, a, b, [(1, _LANES)], taps)
    val = out[:, 0, 0].sum() / (bsz * h * w)
    return val, (a, b)


def _ssim_bwd(window, sigma, res, g):
    a, b = res
    bsz, h, w = _shape3(a)
    taps = _taps(window, sigma)
    ga, gb = _run(_bwd_kernel, a, b, [(h, w), (h, w)], taps)
    scale = g / (bsz * h * w)
    ga = (scale * ga).reshape(a.shape).astype(a.dtype)
    gb = (scale * gb).reshape(b.shape).astype(b.dtype)
    return ga, gb


fused_ssim_mean.defvjp(_ssim_fwd, _ssim_bwd)


def fused_ssim_loss(logits, targets, *, window_size: int = 11,
                    sigma: float = 1.5):
    """1 − SSIM(sigmoid(logits), targets) — drop-in for
    ``losses.ssim.ssim_loss`` on single-channel maps."""
    p = jax.nn.sigmoid(logits.astype(jnp.float32))
    return 1.0 - fused_ssim_mean(p, targets.astype(jnp.float32),
                                 window_size, sigma)
