"""Shared scoped-VMEM compiler-params rule for the Pallas kernels.

First real-v5e exposure (round 2, pallas/dynamic_filter.py): XLA's
memory-space assignment can park a custom call's full output in VMEM
and die against the default 16 MB scoped limit even when the per-grid-
step windows are tiny.  v5e/v4 have 128 MB/core; raising the scoped
ceiling to 100 MB compiles and runs.  ADVICE r3: gate the raise on a
SMALL-VMEM **denylist** (v2/v3, ~16 MB/core — a limit past physical
VMEM fails the compile there) rather than a big-VMEM allowlist, with a
word-bounded regex so e.g. 'v23'/'TPU v4 lite' never mismatch; unknown
and future generations default to the raised limit.  Each kernel keeps
its own env-var escape hatch (0 = compiler default).
"""

from __future__ import annotations

import re

import jax
from jax.experimental.pallas import tpu as pltpu

# jax >= 0.6 renamed TPUCompilerParams -> CompilerParams; take
# whichever this jax ships (the utils/compat.py version-skew posture —
# same vmem_limit_bytes keyword either way).
CompilerParams = getattr(pltpu, "CompilerParams", None) or \
    pltpu.TPUCompilerParams


def scoped_vmem_params(env_var: str) -> "CompilerParams":
    """The per-kernel scoped-VMEM ceiling, overridable via ``env_var``
    (MB; 0 or negative = compiler default; must be a registered
    program-affecting knob — utils/envvars.py)."""
    from ..utils import envvars

    env = envvars.read(env_var)
    if env is not None:
        mb = int(env)
        return (CompilerParams() if mb <= 0
                else CompilerParams(vmem_limit_bytes=mb * 1024 * 1024))
    try:
        kind = jax.devices()[0].device_kind.lower()
    except Exception:  # noqa: BLE001 — no backend: assume modern
        kind = ""
    # "tpu v2" / "tpu v3" (word-bounded so "v23"/"v32" never match).
    if re.search(r"\bv[23]\b", kind) is not None:
        return CompilerParams()
    return CompilerParams(vmem_limit_bytes=100 * 1024 * 1024)
