"""Pallas fused BCE+IoU+CEL loss reductions (SURVEY.md §2.2).

The hybrid SOD loss needs, per side output: the stable-BCE sum and the
per-image region sums Σσ(x)·t, Σσ(x), Σt.  Left to XLA these are four
reduction trees over the same [B,H,W] logits; the kernel here computes
all four in ONE pass over VMEM-resident tiles — logits and targets are
read from HBM exactly once per level (the loss is HBM-bound, SURVEY.md
§6's governing constraint).

The backward pass is elementwise given the forward's per-image scalars
(∂BCE/∂x = σ(x)−t; ∂IoU and ∂CEL are rational functions of the saved
sums), so the custom VJP recomputes it in plain XLA where it fuses into
the backbone's gradient epilogue for free — no second kernel needed.

Gated by ``LossConfig.fused_kernel``; numerically identical (tested) to
the reference-parity losses in ``losses/``.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

_LANES = 128  # TPU lane width: the per-image sums ride one lane row.


def _sums_kernel(x_ref, t_ref, out_ref):
    """One image per grid step: [1,N/128,128] logits/targets →
    [1,1,128] sums (lane 0: BCE sum, 1: Σpt, 2: Σp, 3: Σt; rest zero).

    The image rides VMEM as (sublanes, lanes) = (N/128, 128) — Mosaic
    requires the trailing block dims to match the array (or be 8/128
    multiples), so the caller reshapes pixels into full-lane rows
    rather than one giant row.
    """
    x = x_ref[:].astype(jnp.float32)
    t = t_ref[:].astype(jnp.float32)
    bce = jnp.sum(jnp.maximum(x, 0.0) - x * t + jnp.log1p(jnp.exp(-jnp.abs(x))))
    p = jax.nn.sigmoid(x)
    inter = jnp.sum(p * t)
    psum = jnp.sum(p)
    tsum = jnp.sum(t)
    lane = lax.broadcasted_iota(jnp.int32, (1, 1, _LANES), 2)
    out = (jnp.where(lane == 0, bce, 0.0) + jnp.where(lane == 1, inter, 0.0)
           + jnp.where(lane == 2, psum, 0.0) + jnp.where(lane == 3, tsum, 0.0))
    out_ref[:] = out


def fused_loss_available(shape) -> bool:
    """True when the fused kernel can run for this logit shape here:
    pixel count a lane multiple (padding would bias the Σσ(x) region
    statistics, so off-lane sizes are rejected, not padded) and a
    backend with a Pallas path (Mosaic on TPU, interpret on CPU).
    Callers fall back to the reference losses otherwise — configs with
    ``loss.fused_kernel=true`` must keep working at odd eval sizes and
    on GPU backends."""
    n = 1
    for d in shape[1:]:
        n *= int(d)
    return n % _LANES == 0 and jax.default_backend() in ("cpu", "tpu")


def pixel_region_sums(logits: jnp.ndarray, targets: jnp.ndarray,
                      interpret: bool | None = None,
                      ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray,
                                 jnp.ndarray]:
    """Per-image (bce_sum, Σσ(x)t, Σσ(x), Σt), each [B], in one pass.

    Accepts [B,H,W,1]/[B,H,W]/[B,N]; pixel count must be a multiple of
    128 (true for every SOD config: 320²=800·128; padded inputs would
    bias Σσ(x) and are rejected).

    ``interpret`` defaults to auto (interpret on CPU, Mosaic on TPU);
    pass False to force the Mosaic lowering, e.g. when exporting for
    platform='tpu' from a CPU host (tests do this to validate the
    hardware path without a chip).
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401

    b = logits.shape[0]
    n = int(jnp.size(logits)) // b
    if n % _LANES:
        raise ValueError(f"pixel count {n} not a multiple of {_LANES}")
    rows = n // _LANES
    x = logits.reshape(b, rows, _LANES)
    t = targets.reshape(b, rows, _LANES)

    out = pl.pallas_call(
        _sums_kernel,
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, rows, _LANES), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, rows, _LANES), lambda i: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, _LANES), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b, 1, _LANES), jnp.float32),
        interpret=(jax.default_backend() == "cpu"
                   if interpret is None else interpret),
    )(x, t)
    return out[:, 0, 0], out[:, 0, 1], out[:, 0, 2], out[:, 0, 3]


@partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5, 6))
def fused_bce_iou_cel(logits, targets, bce_w: float = 1.0,
                      iou_w: float = 1.0, cel_w: float = 0.0,
                      iou_eps: float = 1.0, cel_eps: float = 1e-6):
    """bce_w·mean(BCE) + iou_w·mean_i(IoU_i) + cel_w·mean_i(CEL_i) —
    exactly ``losses.bce_with_logits/iou_loss/cel_loss`` combined."""
    loss, _ = _fwd(logits, targets, bce_w, iou_w, cel_w, iou_eps, cel_eps)
    return loss


def _terms(bce, inter, psum, tsum, n_pix, bce_w, iou_w, cel_w,
           iou_eps, cel_eps):
    b = bce.shape[0]
    total = jnp.float32(0.0)
    if bce_w:
        total += bce_w * bce.sum() / (b * n_pix)
    if iou_w:
        union = psum + tsum - inter
        total += iou_w * jnp.mean(1.0 - (inter + iou_eps) / (union + iou_eps))
    if cel_w:
        tot = psum + tsum
        total += cel_w * jnp.mean((tot - 2.0 * inter) / (tot + cel_eps))
    return total


def _fwd(logits, targets, bce_w, iou_w, cel_w, iou_eps, cel_eps):
    bce, inter, psum, tsum = pixel_region_sums(logits, targets)
    n_pix = int(jnp.size(logits) // logits.shape[0])
    loss = _terms(bce, inter, psum, tsum, n_pix, bce_w, iou_w, cel_w,
                  iou_eps, cel_eps)
    return loss, (logits, targets, inter, psum, tsum)


def _bwd(bce_w, iou_w, cel_w, iou_eps, cel_eps, res, g):
    logits, targets, inter, psum, tsum = res
    b = logits.shape[0]
    n_pix = int(jnp.size(logits) // b)
    shape = logits.shape
    x = logits.reshape(b, -1).astype(jnp.float32)
    t = targets.reshape(b, -1).astype(jnp.float32)
    p = jax.nn.sigmoid(x)
    grad = jnp.zeros_like(x)
    if bce_w:
        grad += bce_w * (p - t) / (b * n_pix)
    # Region terms: scalar coefficients per image, broadcast over pixels;
    # dp/dx = p(1−p).
    if iou_w:
        union = (psum + tsum - inter)[:, None]
        i_e = (inter + iou_eps)[:, None]
        u_e = union + iou_eps
        d_dp = -(t * u_e - i_e * (1.0 - t)) / (u_e * u_e)
        grad += iou_w / b * d_dp * p * (1.0 - p)
    if cel_w:
        tot = (psum + tsum)[:, None]
        i2 = (2.0 * inter)[:, None]
        d_dp = ((1.0 - 2.0 * t) * (tot + cel_eps) - (tot - i2)) / (
            (tot + cel_eps) ** 2)
        grad += cel_w / b * d_dp * p * (1.0 - p)
    grad = (g * grad).reshape(shape).astype(logits.dtype)
    return grad, jnp.zeros_like(targets)


fused_bce_iou_cel.defvjp(_fwd, _bwd)
