from .dynamic_filter import fused_dynamic_filter
from .flash_attention import flash_attention
from .fused_loss import fused_bce_iou_cel, pixel_region_sums
from .fused_resample import (
    fused_resample_available,
    fused_upsample2,
    fused_upsample2_merge,
)
from .fused_ssim import (
    fused_ssim_available,
    fused_ssim_loss,
    fused_ssim_mean,
)

__all__ = [
    "flash_attention",
    "fused_dynamic_filter",
    "fused_bce_iou_cel",
    "fused_resample_available",
    "fused_ssim_available",
    "fused_ssim_loss",
    "fused_ssim_mean",
    "fused_upsample2",
    "fused_upsample2_merge",
    "pixel_region_sums",
]
