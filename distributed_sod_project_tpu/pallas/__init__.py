from .fused_loss import fused_bce_iou_cel, pixel_region_sums

__all__ = ["fused_bce_iou_cel", "pixel_region_sums"]
