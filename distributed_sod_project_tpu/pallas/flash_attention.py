"""Pallas flash attention — the ViT-SOD hot op (SURVEY.md §2.2, §5).

``models/vit_sod.py`` is the long-context zoo member: global attention
over every patch token, quadratic in resolution.  The XLA path
(``parallel/ring_attention.full_attention``) materialises the [N, N]
score matrix in HBM — at 1024px/patch16 that is 4096² floats *per head*
per block, which is exactly the memory wall flash attention exists to
remove.  This kernel computes attention tile-by-tile in VMEM with an
online softmax: HBM traffic is O(N·D) (read q/k/v, write out + one
lse row) instead of O(N²).

Design (mirrors the layout conventions of the other kernels here):

- Heads-major [B, H, N, D] public layout (``ring_attention``'s), folded
  to [B·H, N, D] for the grid.  N is zero-padded to a multiple of the
  128-lane tile; padded KEY columns are masked with a large negative
  bias (never ``-inf`` — a fully-finite path keeps ``exp`` NaN-free),
  padded QUERY rows compute garbage that the wrapper slices off, and
  their zero upstream gradients keep the backward exact.
- Running (m, l) softmax statistics live in VMEM scratch as
  (block_q, 128) lane-replicated tiles (the Mosaic-native layout),
  carried across the innermost KV grid dimension; the accumulator is
  rescaled once per visiting block and divided once at the end.
- The MXU sees three dots per tile pair — q·kᵀ, p·v, and (backward)
  ds·k / dsᵀ·q / pᵀ·do — all with ``preferred_element_type=float32``;
  ``p`` is cast to the value dtype so bf16 inputs ride the MXU at full
  rate.
- Backward is two more kernels (custom VJP, no O(N²) residual): dq
  accumulates over KV blocks; dk/dv swap the grid so the KV block is
  resident while Q blocks stream past.  Both rebuild ``p`` from the
  saved lse row, flash-attention style; ``delta = Σ do·out`` is reduced
  in-kernel from the streamed q/out tiles.

Exactness: forward AND gradients match the XLA oracle to float32
round-off (tests/test_pallas_flash.py); the real-TPU Mosaic lowering is
guarded by ``jax.export(platforms=['tpu'])`` tests, same as
fused_ssim/fused_loss.
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANES = 128
# Large-but-finite mask bias (the official TPU kernels' choice): keeps
# every intermediate finite so exp/max never see -inf - -inf = NaN.
_MASK_VALUE = -0.7 * float(np.finfo(np.float32).max)


def _widen(x, n: int):
    """Lane-replicated (rows, 128) tile -> (rows, n): slice for n < 128,
    tile for multiples of 128 (the Mosaic-proven broadcast pattern)."""
    if n < _LANES:
        return x[:, :n]
    reps, rem = divmod(n, _LANES)
    if rem:
        raise ValueError(f"width {n} not a multiple of {_LANES}")
    return jnp.tile(x, (1, reps)) if reps > 1 else x


def _key_mask_bias(j, bkv: int, bq: int, n: int):
    """(bq, bkv) additive bias masking key columns >= n (padding)."""
    col = lax.broadcasted_iota(jnp.int32, (bq, bkv), 1) + j * bkv
    return jnp.where(col < n, 0.0, _MASK_VALUE).astype(jnp.float32)


def _scores(q_ref, k_ref, blk, *, scale, n, padded):
    """(bq, bkv) masked, scaled logits for one tile pair; ``blk`` is the
    kv-block index the key columns belong to."""
    s = lax.dot_general(q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32) * scale
    if padded:
        s = s + _key_mask_bias(blk, k_ref.shape[1], q_ref.shape[1], n)
    return s


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_s, l_s, acc_s, *, scale: float, n: int, padded: bool):
    j = pl.program_id(2)
    nj = pl.num_programs(2)
    d = acc_s.shape[1]

    @pl.when(j == 0)
    def _():
        m_s[...] = jnp.full(m_s.shape, _MASK_VALUE, jnp.float32)
        l_s[...] = jnp.zeros(l_s.shape, jnp.float32)
        acc_s[...] = jnp.zeros(acc_s.shape, jnp.float32)

    s = _scores(q_ref, k_ref, j, scale=scale, n=n, padded=padded)

    m_prev = m_s[...]                                   # (bq, 128)
    m_curr = jnp.max(s, axis=1)[:, None]                # (bq, 1)
    m_next = jnp.maximum(m_prev, m_curr)                # (bq, 128)
    p = jnp.exp(s - _widen(m_next, k_ref.shape[1]))     # (bq, bkv)
    corr = jnp.exp(m_prev - m_next)                     # (bq, 128)
    l_s[...] = l_s[...] * corr + jnp.sum(p, axis=1)[:, None]
    m_s[...] = m_next
    pv = lax.dot_general(p.astype(v_ref.dtype), v_ref[0],
                         (((1,), (0,)), ((), ())),
                         preferred_element_type=jnp.float32)
    acc_s[...] = acc_s[...] * _widen(corr, d) + pv

    @pl.when(j == nj - 1)
    def _():
        l_safe = jnp.where(l_s[...] == 0.0, 1.0, l_s[...])
        o_ref[0] = (acc_s[...] / _widen(l_safe, d)).astype(o_ref.dtype)
        lse_ref[0] = m_s[...] + jnp.log(l_safe)


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               dq_s, *, scale: float, n: int, padded: bool):
    j = pl.program_id(2)
    nj = pl.num_programs(2)

    @pl.when(j == 0)
    def _():
        dq_s[...] = jnp.zeros(dq_s.shape, jnp.float32)

    s = _scores(q_ref, k_ref, j, scale=scale, n=n, padded=padded)
    p = jnp.exp(s - _widen(lse_ref[0], k_ref.shape[1]))
    do = do_ref[0].astype(jnp.float32)
    dp = lax.dot_general(do, v_ref[0].astype(jnp.float32),
                         (((1,), (1,)), ((), ())),
                         preferred_element_type=jnp.float32)
    ds = p * (dp - delta_ref[0][:, :1]) * scale
    dq_s[...] += lax.dot_general(ds.astype(k_ref.dtype), k_ref[0],
                                 (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)

    @pl.when(j == nj - 1)
    def _():
        dq_ref[0] = dq_s[...].astype(dq_ref.dtype)


def _dkv_kernel(k_ref, v_ref, q_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_s, dv_s,
                *, scale: float, n: int, padded: bool):
    i = pl.program_id(1)      # kv block (resident)
    j = pl.program_id(2)      # q block (streams past)
    nj = pl.num_programs(2)

    @pl.when(j == 0)
    def _():
        dk_s[...] = jnp.zeros(dk_s.shape, jnp.float32)
        dv_s[...] = jnp.zeros(dv_s.shape, jnp.float32)

    s = _scores(q_ref, k_ref, i, scale=scale, n=n, padded=padded)
    p = jnp.exp(s - _widen(lse_ref[0], k_ref.shape[1]))
    do = do_ref[0].astype(jnp.float32)
    # dv += pᵀ · do   (contract over the q rows)
    dv_s[...] += lax.dot_general(p.astype(do_ref.dtype), do_ref[0],
                                 (((0,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
    dp = lax.dot_general(do, v_ref[0].astype(jnp.float32),
                         (((1,), (1,)), ((), ())),
                         preferred_element_type=jnp.float32)
    ds = p * (dp - delta_ref[0][:, :1]) * scale
    dk_s[...] += lax.dot_general(ds.astype(q_ref.dtype), q_ref[0],
                                 (((0,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)

    @pl.when(j == nj - 1)
    def _():
        dk_ref[0] = dk_s[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_s[...].astype(dv_ref.dtype)


# ---------------------------------------------------------------------------
# wrappers
# ---------------------------------------------------------------------------


def _pad_n(x, np_):
    pad = np_ - x.shape[1]
    return x if pad == 0 else jnp.pad(x, ((0, 0), (0, pad), (0, 0)))


def _specs(bq, bkv, d, *, kv_resident: bool):
    """BlockSpecs for the two grid orders.  ``kv_resident=False``: grid
    (bh, qi, kj) — q-like blocks follow dim 1, kv-like dim 2.
    ``kv_resident=True``: grid (bh, ki, qj) — swapped."""
    if kv_resident:
        q_ix = lambda b, i, j: (b, j, 0)
        kv_ix = lambda b, i, j: (b, i, 0)
    else:
        q_ix = lambda b, i, j: (b, i, 0)
        kv_ix = lambda b, i, j: (b, j, 0)
    qs = pl.BlockSpec((1, bq, d), q_ix)
    kv = pl.BlockSpec((1, bkv, d), kv_ix)
    row = pl.BlockSpec((1, bq, _LANES), q_ix)
    return qs, kv, row


def _fwd_call(q, k, v, cfg):
    bq, bkv, interpret, n = cfg
    bh, np_, d = q.shape
    qs, kvs, row = _specs(bq, bkv, d, kv_resident=False)
    return pl.pallas_call(
        partial(_fwd_kernel, scale=1.0 / d**0.5, n=n, padded=np_ != n),
        grid=(bh, np_ // bq, np_ // bkv),
        in_specs=[qs, kvs, kvs],
        out_specs=[qs, row],
        out_shape=[jax.ShapeDtypeStruct((bh, np_, d), q.dtype),
                   jax.ShapeDtypeStruct((bh, np_, _LANES), jnp.float32)],
        scratch_shapes=[pltpu.VMEM((bq, _LANES), jnp.float32),
                        pltpu.VMEM((bq, _LANES), jnp.float32),
                        pltpu.VMEM((bq, d), jnp.float32)],
        cost_estimate=pl.CostEstimate(
            flops=4 * bh * np_ * np_ * d,
            transcendentals=bh * np_ * np_,
            bytes_accessed=4 * q.size * q.dtype.itemsize),
        interpret=interpret,
    )(q, k, v)


def _bwd_call(q, k, v, out, lse_row, do, cfg, dlse=None):
    bq, bkv, interpret, n = cfg
    bh, np_, d = q.shape
    scale = 1.0 / d**0.5
    # delta_i = Σ_d out·do — loop-invariant per query row, so computed
    # ONCE here (one fused XLA pass) and streamed to both kernels as a
    # lane-replicated row tile.  A cotangent on lse folds in exactly
    # here: ∂lse_i/∂s_ij = p_ij, so
    # s̄_ij = p_ij·(dp_ij − delta_i + dlse_i) — i.e. dlse just shifts
    # delta, and the kernels need no second code path.
    delta = jnp.sum(out.astype(jnp.float32) * do.astype(jnp.float32),
                    axis=-1)
    if dlse is not None:
        delta = delta - dlse.astype(jnp.float32)
    delta = jnp.broadcast_to(delta[..., None], (bh, np_, _LANES))
    # lse is saved as one lane per row ((bh, np), 1/128th the tile the
    # kernels stream) and re-broadcast here, same as delta.
    lse = jnp.broadcast_to(lse_row[..., None], (bh, np_, _LANES))

    qs, kvs, row = _specs(bq, bkv, d, kv_resident=False)
    dq = pl.pallas_call(
        partial(_dq_kernel, scale=scale, n=n, padded=np_ != n),
        grid=(bh, np_ // bq, np_ // bkv),
        in_specs=[qs, kvs, kvs, qs, row, row],
        out_specs=qs,
        out_shape=jax.ShapeDtypeStruct((bh, np_, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        cost_estimate=pl.CostEstimate(
            flops=6 * bh * np_ * np_ * d,
            transcendentals=bh * np_ * np_,
            bytes_accessed=6 * q.size * q.dtype.itemsize),
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    qs, kvs, row = _specs(bq, bkv, d, kv_resident=True)
    dk, dv = pl.pallas_call(
        partial(_dkv_kernel, scale=scale, n=n, padded=np_ != n),
        grid=(bh, np_ // bkv, np_ // bq),
        in_specs=[kvs, kvs, qs, qs, row, row],
        out_specs=[kvs, kvs],
        out_shape=[jax.ShapeDtypeStruct((bh, np_, d), k.dtype),
                   jax.ShapeDtypeStruct((bh, np_, d), v.dtype)],
        scratch_shapes=[pltpu.VMEM((bkv, d), jnp.float32),
                        pltpu.VMEM((bkv, d), jnp.float32)],
        cost_estimate=pl.CostEstimate(
            flops=10 * bh * np_ * np_ * d,
            transcendentals=bh * np_ * np_,
            bytes_accessed=6 * q.size * q.dtype.itemsize),
        interpret=interpret,
    )(k, v, q, do, lse, delta)
    return dq, dk, dv


@partial(jax.custom_vjp, nondiff_argnums=(3,))
def _flash_lse(q, k, v, cfg):
    """The forward+lse primitive ([bh, np] f32 lse — the merge
    statistic ring attention needs; the plain wrapper drops it)."""
    out, lse = _fwd_call(q, k, v, cfg)
    return out, lse[:, :, 0]


def _flash_lse_fwd(q, k, v, cfg):
    out, lse = _fwd_call(q, k, v, cfg)
    # Residuals keep ONE lane of the lane-replicated lse tile — the
    # backward re-broadcasts; holding all 128 copies across the
    # fwd→bwd gap would rival the q/k/v residuals themselves.
    return (out, lse[:, :, 0]), (q, k, v, out, lse[:, :, 0])


def _flash_lse_bwd(cfg, res, gs):
    q, k, v, out, lse_row = res
    g_out, g_lse = gs
    return _bwd_call(q, k, v, out, lse_row, g_out, cfg, dlse=g_lse)


_flash_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)


def _prepare(q, k, v, block_q, block_kv, interpret):
    """Validate, fold heads into batch, pad N; returns folded q/k/v,
    the static kernel cfg, and the original (b, h, n, d)."""
    if q.shape != k.shape or q.shape != v.shape:
        raise ValueError(f"q/k/v shapes differ: {q.shape} {k.shape} {v.shape}")
    if q.ndim != 4:
        raise ValueError(f"expected [B, H, N, D], got {q.shape}")
    if block_q is None:
        block_q = _env_block("DSOD_FLASH_BLOCK_Q", 128)
    if block_kv is None:
        block_kv = _env_block("DSOD_FLASH_BLOCK_KV", 128)
    b, h, n, d = q.shape
    if d > _LANES and d % _LANES:
        raise ValueError(
            f"head dim {d} unsupported (need <= {_LANES} or a multiple); "
            "use parallel.ring_attention.full_attention")
    if block_q % _LANES or block_kv % _LANES:
        raise ValueError("block sizes must be multiples of 128")
    # Pad to a COMMON multiple of both blocks — rounding to only the
    # larger would leave valid rows uncovered by the floor-divided grid
    # whenever the blocks don't divide each other.
    step = math.lcm(block_q, block_kv)
    np_ = -(-n // step) * step
    interpret = jax.default_backend() == "cpu" if interpret is None else interpret
    cfg = (min(block_q, np_), min(block_kv, np_), interpret, n)
    fold = lambda t: _pad_n(t.reshape(b * h, n, d), np_)
    return fold(q), fold(k), fold(v), cfg, (b, h, n, d)


def _env_block(name: str, default: int) -> int:
    """Block-shape override for on-hardware tuning
    (``DSOD_FLASH_BLOCK_Q`` / ``DSOD_FLASH_BLOCK_KV`` — the knob
    ``tools/bench_flash.py`` sweeps; round-2 v5e measurement showed the
    128/128 default leaves >2x on the table at short N)."""
    from ..utils import envvars

    return envvars.read_int(name, default)


def flash_attention(q, k, v, *, block_q: int | None = None,
                    block_kv: int | None = None,
                    interpret: bool | None = None) -> jnp.ndarray:
    """Drop-in for ``ring_attention.full_attention`` (non-causal).

    q/k/v: [B, H, N, D] (any N; zero-padded internally to the 128-lane
    tile), D ≤ 128 or a multiple of 128.  Differentiable via the Pallas
    backward kernels.  ``interpret`` defaults to auto (interpret on
    CPU, Mosaic on TPU).
    """
    qf, kf, vf, cfg, (b, h, n, d) = _prepare(q, k, v, block_q, block_kv,
                                             interpret)
    # Single custom-VJP definition shared with the lse variant: the
    # dropped lse output arrives in the backward as a zero cotangent,
    # which reduces the dlse delta-shift to a no-op subtract.
    out, _ = _flash_lse(qf, kf, vf, cfg)
    return out[:, :n].reshape(b, h, n, d)


def flash_attention_with_lse(q, k, v, *, block_q: int | None = None,
                             block_kv: int | None = None,
                             interpret: bool | None = None):
    """``flash_attention`` that also returns lse ([B, H, N] f32, the
    per-row logsumexp of the scaled scores) — the statistic that makes
    per-block results mergeable, which is how the SP ring composes
    flash blocks (parallel/ring_attention.py).  Both outputs are
    differentiable: an lse cotangent folds into the same backward
    kernels as a shift of delta."""
    qf, kf, vf, cfg, (b, h, n, d) = _prepare(q, k, v, block_q, block_kv,
                                             interpret)
    out, lse = _flash_lse(qf, kf, vf, cfg)
    return (out[:, :n].reshape(b, h, n, d),
            lse[:, :n].reshape(b, h, n))
