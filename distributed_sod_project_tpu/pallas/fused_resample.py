"""Pallas fused resample-merge — the fine-resolution decoder idiom.

The round-4 roofline reconciliation (docs/PERFORMANCE.md) put ~125 ms
of the 270 ms flagship step in the 160/80 buckets running 3.3x/2.1x off
streaming bandwidth, and named the decoder resample+merge chain as the
one place a kernel can repay.  The idiom — shared by MINet's AIM/SIM,
HDFNet's top-down decoder, GateNet's skip path and U²-Net's nested
U-merges — is::

    up   = 2x bilinear upsample(d)          # coarse -> fine
    out  = up + lateral        (add merge)  # or
    out  = concat(up, lateral) (concat merge)

On the XLA path each fine-resolution map crosses HBM several times: the
upsample writes ``up``, the merge reads ``up`` + ``lateral`` and writes
``out`` (plus the interleave's relayout copies the round-2 trace
surfaced).  This kernel runs the whole chain as ONE VMEM-resident pass
per image: read the coarse map (a quarter of the fine bytes) and the
lateral once, write the merged output once.

Numerics are identical to ``models/layers.py::resize_to``'s factor-2
fast path (itself ``jax.image.resize(method='bilinear')``-exact:
half-pixel centers, edge taps renormalised == index clamping)::

    out[2i]   = 0.25*x[i-1] + 0.75*x[i]     (x[-1] -> x[0])
    out[2i+1] = 0.75*x[i]   + 0.25*x[i+1]   (x[n]  -> x[n-1])

applied separably H then W.  The in-kernel interleave uses the same
concat-in-next-axis form the layout-stable XLA path uses (a VMEM
shuffle here, never an HBM relayout).

Backward is a closed form, not a recompute: the op is linear in both
operands, so ``d_lateral`` is the cotangent (or its channel slab) and
``d_x`` is the transposed resample — per axis, with ``ge = g[2j]``,
``go = g[2j+1]``::

    dx[j] = 0.75*(ge[j] + go[j]) + 0.25*(go[j-1] + ge[j+1])

where the out-of-range taps fold the edge clamping in exactly:
``go[-1] -> ge[0]`` and ``ge[n] -> go[n-1]`` (the clamped forward taps
contribute 0.25*g[0] / 0.25*g[2n-1] to the edge gradients).  That runs
as a second gather-form kernel with the axes applied in reverse order.

Like the other kernels here: one image per grid step, a VMEM budget
guard with fallback handled by the caller (``layers.resample_merge``),
``interpret`` auto (interpret on CPU, Mosaic on TPU), parity + the
Mosaic lowering guarded in tests/test_pallas_resample.py via
``jax.export(platforms=['tpu'])``.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax >= 0.6 renamed TPUCompilerParams -> CompilerParams (the
# utils/compat.py version-skew posture, as in dynamic_filter.py).
_CompilerParams = getattr(pltpu, "CompilerParams", None) or \
    pltpu.TPUCompilerParams

# f32-element budget for ONE grid step's tiles (padded coarse input +
# lateral + merged output).  6M elems ~= 24 MB f32 against the 100 MB
# scoped-VMEM ceiling — sized so EVERY flagship fine-decoder site fits,
# including the largest, SIM-0's concat merge (80x80x32 up into
# 160x160x64 -> 96ch out = 4.31M elems, which a 4M budget silently
# excluded — exactly the 160-bucket stage lever #1 targets).  Oversize
# maps (e.g. U²-Net's full-width 160->320 concat, 21M elems) fall back
# to the XLA path via ``fused_resample_available``; v2/v3 (~16 MB/core)
# would need DSOD_RESAMPLE_VMEM_MB=0 plus a smaller budget, but the
# fused arm is a knob-gated experiment aimed at v4+/v5e.
_MAX_TILE_ELEMS = 6 * 1024 * 1024


def _compiler_params() -> "_CompilerParams":
    """Scoped-VMEM ceiling via the shared v2/v3 small-VMEM denylist
    rule (pallas/vmem_budget.py); ``DSOD_RESAMPLE_VMEM_MB`` overrides
    either way (0 = compiler default)."""
    from .vmem_budget import scoped_vmem_params

    return scoped_vmem_params("DSOD_RESAMPLE_VMEM_MB")


def _interpret(interpret):
    return jax.default_backend() == "cpu" if interpret is None else interpret


def _img_spec(shape):
    """BlockSpec for one image per grid step over the leading dim."""
    n = len(shape)
    return pl.BlockSpec((1,) + tuple(shape),
                        lambda i, _n=n: (i,) + (0,) * _n)


def _ileave(e, o, axis):
    """Interleave two equal blocks along ``axis``: out[2i]=e[i],
    out[2i+1]=o[i].  Concat-in-next-axis + merge reshape — the same
    row-major identity the layout-stable XLA interleave uses."""
    t = jnp.concatenate([e, o], axis=axis + 1)
    shape = list(e.shape)
    shape[axis] *= 2
    return t.reshape(tuple(shape))


def _clamp_pad(x):
    """Edge-replicate pad by 1 in both spatial dims — VALUE-level, so
    the padded map lives only in VMEM.  (An earlier draft jnp.pad'ed
    outside the pallas_call, which materialized the padded coarse copy
    in HBM and silently gave back ~2/3 of the per-site saving the
    kernel exists for.)"""
    x = jnp.concatenate([x[0:1], x, x[-1:]], axis=0)
    return jnp.concatenate([x[:, 0:1], x, x[:, -1:]], axis=1)


def _up2_vals(x):
    """(h, w, C) f32 tile -> (2h, 2w, C) upsampled (clamped edges)."""
    h, w = x.shape[0], x.shape[1]
    xp = _clamp_pad(x)                             # (h+2, w+2, C), VMEM
    e = 0.25 * xp[0:h] + 0.75 * xp[1:h + 1]
    o = 0.75 * xp[1:h + 1] + 0.25 * xp[2:h + 2]
    y = _ileave(e, o, axis=0)                      # (2h, w+2, C)
    ew = 0.25 * y[:, 0:w] + 0.75 * y[:, 1:w + 1]
    ow = 0.75 * y[:, 1:w + 1] + 0.25 * y[:, 2:w + 2]
    return _ileave(ew, ow, axis=1)                 # (2h, 2w, C)


def _up_kernel(x_ref, o_ref):
    o_ref[0] = _up2_vals(x_ref[0].astype(jnp.float32)).astype(o_ref.dtype)


def _up_add_kernel(x_ref, lat_ref, o_ref):
    up = _up2_vals(x_ref[0].astype(jnp.float32))
    o_ref[0] = (up + lat_ref[0].astype(jnp.float32)).astype(o_ref.dtype)


def _up_cat_kernel(x_ref, lat_ref, o_ref, *, cx, x_first):
    up = _up2_vals(x_ref[0].astype(jnp.float32)).astype(o_ref.dtype)
    lat = lat_ref[0].astype(o_ref.dtype)
    if x_first:
        o_ref[0, :, :, :cx] = up
        o_ref[0, :, :, cx:] = lat
    else:
        cl = lat.shape[-1]
        o_ref[0, :, :, :cl] = lat
        o_ref[0, :, :, cl:] = up


def _deint_T(g, axis):
    """One axis of the transposed upsample: (…, 2n, …) -> (…, n, …).

    Splits even/odd phases by the inverse of the interleave reshape,
    then applies ``dx = 0.75*(ge+go) + 0.25*(go<<1 + ge>>1)`` with the
    edge-clamp corrections folded into the shifted operands
    (``go[-1] -> ge[0]``, ``ge[n] -> go[n-1]`` — derivation in the
    module docstring)."""
    n = g.shape[axis] // 2
    shape = list(g.shape)
    shape[axis] = n
    shape[axis + 1] *= 2
    t = g.reshape(tuple(shape))                    # inverse interleave
    m = g.shape[axis + 1]
    ge = lax.slice_in_dim(t, 0, m, axis=axis + 1)
    go = lax.slice_in_dim(t, m, 2 * m, axis=axis + 1)
    if n == 1:  # both shifts degenerate to the other phase's only row
        return ge + go
    go_shift = jnp.concatenate(  # go[j-1], with go[-1] := ge[0]
        [lax.slice_in_dim(ge, 0, 1, axis=axis),
         lax.slice_in_dim(go, 0, n - 1, axis=axis)], axis)
    ge_shift = jnp.concatenate(  # ge[j+1], with ge[n] := go[n-1]
        [lax.slice_in_dim(ge, 1, n, axis=axis),
         lax.slice_in_dim(go, n - 1, n, axis=axis)], axis)
    return 0.75 * (ge + go) + 0.25 * (go_shift + ge_shift)


def _upT_kernel(g_ref, dx_ref):
    g = g_ref[0].astype(jnp.float32)
    dx = _deint_T(_deint_T(g, axis=1), axis=0)  # reverse of fwd order
    dx_ref[0] = dx.astype(dx_ref.dtype)


def _call_up(x, interpret):
    b, h, w, c = x.shape
    return pl.pallas_call(
        _up_kernel,
        grid=(b,),
        in_specs=[_img_spec(x.shape[1:])],
        out_specs=_img_spec((2 * h, 2 * w, c)),
        out_shape=jax.ShapeDtypeStruct((b, 2 * h, 2 * w, c), x.dtype),
        cost_estimate=pl.CostEstimate(
            flops=16.0 * b * h * w * c, transcendentals=0,
            bytes_accessed=(x.size + 4 * b * h * w * c) * 4),
        interpret=interpret,
        compiler_params=_compiler_params(),
    )(x)


def _call_merge(x, lat, mode, x_first, interpret):
    b, h, w, c = x.shape
    cl = lat.shape[-1]
    c_out = c + cl if mode == "concat" else c
    if mode == "add":
        kernel = _up_add_kernel
    else:
        kernel = partial(_up_cat_kernel, cx=c, x_first=x_first)
    return pl.pallas_call(
        kernel,
        grid=(b,),
        in_specs=[_img_spec(x.shape[1:]), _img_spec(lat.shape[1:])],
        out_specs=_img_spec((2 * h, 2 * w, c_out)),
        out_shape=jax.ShapeDtypeStruct((b, 2 * h, 2 * w, c_out), x.dtype),
        cost_estimate=pl.CostEstimate(
            flops=(16.0 + 4.0) * b * h * w * c, transcendentals=0,
            bytes_accessed=(x.size + lat.size
                            + 4 * b * h * w * c_out) * 4),
        interpret=interpret,
        compiler_params=_compiler_params(),
    )(x, lat)


def _call_upT(g, interpret):
    b, hh, ww, c = g.shape
    return pl.pallas_call(
        _upT_kernel,
        grid=(b,),
        in_specs=[_img_spec(g.shape[1:])],
        out_specs=_img_spec((hh // 2, ww // 2, c)),
        out_shape=jax.ShapeDtypeStruct((b, hh // 2, ww // 2, c), g.dtype),
        interpret=interpret,
        compiler_params=_compiler_params(),
    )(g)


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def _up2(x, interpret):
    return _call_up(x, interpret)


def _up2_fwd(x, interpret):
    return _call_up(x, interpret), None


def _up2_bwd(interpret, _, g):
    return (_call_upT(g, interpret),)


_up2.defvjp(_up2_fwd, _up2_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(2,))
def _up2_add(x, lat, interpret):
    return _call_merge(x, lat, "add", True, interpret)


def _up2_add_fwd(x, lat, interpret):
    return _call_merge(x, lat, "add", True, interpret), None


def _up2_add_bwd(interpret, _, g):
    return _call_upT(g, interpret), g


_up2_add.defvjp(_up2_add_fwd, _up2_add_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _up2_cat(x, lat, cx, x_first, interpret):
    return _call_merge(x, lat, "concat", x_first, interpret)


def _up2_cat_fwd(x, lat, cx, x_first, interpret):
    return _call_merge(x, lat, "concat", x_first, interpret), None


def _up2_cat_bwd(cx, x_first, interpret, _, g):
    if x_first:
        gx, glat = g[..., :cx], g[..., cx:]
    else:
        gx, glat = g[..., g.shape[-1] - cx:], g[..., :g.shape[-1] - cx]
    return _call_upT(gx, interpret), glat


_up2_cat.defvjp(_up2_cat_fwd, _up2_cat_bwd)


def fused_resample_available(x_shape, out_hw, mode: str = "none",
                             lat_channels: int = 0) -> bool:
    """True when the fused kernel applies: the target is exactly a 2x
    upsample per axis AND one grid step's tiles (padded coarse input +
    lateral + merged output, f32) fit the VMEM budget.  Callers fall
    back to the XLA path otherwise (same numerics, no fusion)."""
    b, h, w, c = x_shape
    if tuple(out_hw) != (2 * h, 2 * w):
        return False
    elems = (h + 2) * (w + 2) * c
    if mode in ("add", "concat"):
        elems += 4 * h * w * lat_channels
    elems += 4 * h * w * (c + (lat_channels if mode == "concat" else 0))
    return elems <= _MAX_TILE_ELEMS


def fused_upsample2(x: jnp.ndarray,
                    interpret: bool | None = None) -> jnp.ndarray:
    """2x bilinear upsample of an NHWC map as one Pallas pass —
    numerics-identical to ``resize_to(x, (2H, 2W))``'s fast path.
    Differentiable (closed-form transposed-resample kernel)."""
    if x.ndim != 4:
        raise ValueError(f"expected NHWC, got {x.shape}")
    return _up2(x, _interpret(interpret))


def fused_upsample2_merge(x: jnp.ndarray, lateral: jnp.ndarray,
                          mode: str = "add", x_first: bool = True,
                          interpret: bool | None = None) -> jnp.ndarray:
    """2x upsample ``x`` to ``lateral``'s spatial size and merge, in one
    VMEM-resident pass.  ``mode='add'`` needs matching channel counts;
    ``mode='concat'`` emits ``[up, lateral]`` channels (``x_first``)
    or ``[lateral, up]``.  Shape/budget gating is the CALLER's job
    (``fused_resample_available`` / ``layers.resample_merge``) — this
    raises on shape mismatch rather than silently falling back."""
    if x.ndim != 4 or lateral.ndim != 4:
        raise ValueError(f"expected NHWC, got {x.shape} / {lateral.shape}")
    b, h, w, c = x.shape
    if lateral.shape[0] != b or lateral.shape[1:3] != (2 * h, 2 * w):
        raise ValueError(
            f"lateral {lateral.shape} is not the 2x target of {x.shape}")
    if mode == "add":
        if lateral.shape[-1] != c:
            raise ValueError(
                f"add merge needs matching channels, got {c} vs "
                f"{lateral.shape[-1]}")
        return _up2_add(x, lateral, _interpret(interpret))
    if mode == "concat":
        return _up2_cat(x, lateral, c, x_first, _interpret(interpret))
    raise ValueError(f"mode must be 'add' or 'concat', got {mode!r}")
