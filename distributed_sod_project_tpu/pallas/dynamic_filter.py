"""Pallas dynamic local filtering — HDFNet's hot op (SURVEY.md §2 C5).

HDFNet applies per-position depthwise kernels predicted from the depth
stream (``models/hdfnet.py::dynamic_local_filter``).  The XLA path is
im2col (``conv_general_dilated_patches``) + einsum: it materialises a
``ksize²``-times-wider patch tensor in HBM per dilation branch — 9×C
channels where the op itself only ever needs C in flight.  This kernel
keeps everything in VMEM: each grid step loads one image's padded
feature tile and kernel maps, and the filtered output is just
``ksize²`` statically-shifted multiply-accumulates on the VPU.  HBM
traffic: read x (+pad) and k once, write out once.

Layouts (chosen for the TPU tiling, not torch parity):

- x / out: NHWC — C on the 128-lane axis.
- kernel maps: [B, ksize², H, W] (tap-major) — W on lanes, one clean
  (H, W) tile per tap instead of a 9-wide minor axis.

Backward is two more gather-form kernels (custom VJP, no scatters):

- ``dx[y'] = Σ_t (k_t ⊙ g)`` read at the MIRRORED shift ``2r − δ_t``
  — the transpose of a shifted gather is a gather at the opposite
  shift, so dx has the same structure as the forward.
- ``dk_t = Σ_c x_shifted ⊙ g`` — a channel reduction per tap.

Like fused_ssim, the grid is one image per step with a VMEM budget
guard: oversize inputs fall back to the XLA im2col path (same math,
asserted in tests).  Parity with that path (forward AND gradients) is
asserted in tests/test_pallas_dynfilter.py; Mosaic lowering is guarded
by ``jax.export(platforms=['tpu'])`` like the other kernels here.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax ≥ 0.6 renamed TPUCompilerParams → CompilerParams; take whichever
# this jax ships (the utils/compat.py version-skew pattern — same
# vmem_limit_bytes keyword either way).
_CompilerParams = getattr(pltpu, "CompilerParams", None) or \
    pltpu.TPUCompilerParams

# Beyond this many f32 elements for the padded x tile, fall back to the
# XLA im2col path rather than risk VMEM pressure (≈8 MB at f32, and the
# kernel maps add T·H·W on top).
_MAX_TILE_ELEMS = 2 * 1024 * 1024

def _compiler_params() -> "_CompilerParams":
    """Per-kernel scoped-VMEM ceiling, gated on the device generation.

    The round-2 compile-failure history and the ADVICE-r3 v2/v3
    small-VMEM denylist rule now live in the shared helper
    (pallas/vmem_budget.py) so every kernel applies the same policy;
    ``DSOD_DLF_VMEM_MB`` stays this kernel's escape hatch (0 =
    compiler default).
    """
    from .vmem_budget import scoped_vmem_params

    return scoped_vmem_params("DSOD_DLF_VMEM_MB")


def _taps(ksize: int, dilation: int):
    """Static (dy, dx) offsets into the r-padded tile, tap-major."""
    offs = [dilation * i for i in range(ksize)]
    return [(dy, dx) for dy in offs for dx in offs]


def _fwd_kernel(x_ref, k_ref, o_ref, *, taps, h, w):
    # x_ref: (1, H+2r, W+2r, C); k_ref: (1, T, H, W); o_ref: (1, H, W, C)
    acc = jnp.zeros(o_ref.shape[1:], jnp.float32)
    for t, (dy, dx) in enumerate(taps):
        xs = x_ref[0, dy:dy + h, dx:dx + w, :].astype(jnp.float32)
        acc = acc + xs * k_ref[0, t][:, :, None].astype(jnp.float32)
    o_ref[0] = acc.astype(o_ref.dtype)


def _dx_kernel(g_ref, k_ref, dx_ref, *, taps, h, w, r2):
    # g_ref: (1, H+2r, W+2r, C) padded cotangent; k_ref: (1, T, H+2r,
    # W+2r) padded kernel maps; dx_ref: (1, H, W, C).
    acc = jnp.zeros(dx_ref.shape[1:], jnp.float32)
    for t, (dy, dx) in enumerate(taps):
        sy, sx = r2 - dy, r2 - dx  # mirrored shift
        gs = g_ref[0, sy:sy + h, sx:sx + w, :].astype(jnp.float32)
        ks = k_ref[0, t, sy:sy + h, sx:sx + w].astype(jnp.float32)
        acc = acc + gs * ks[:, :, None]
    dx_ref[0] = acc.astype(dx_ref.dtype)


def _dk_kernel(x_ref, g_ref, dk_ref, *, taps, h, w):
    # x_ref: (1, H+2r, W+2r, C); g_ref: (1, H, W, C); dk_ref: (1, T, H, W)
    g = g_ref[0].astype(jnp.float32)
    for t, (dy, dx) in enumerate(taps):
        xs = x_ref[0, dy:dy + h, dx:dx + w, :].astype(jnp.float32)
        dk_ref[0, t] = jnp.sum(xs * g, axis=-1)


def _interpret(interpret):
    return jax.default_backend() == "cpu" if interpret is None else interpret


def _pad_hw(x, r):
    return jnp.pad(x, ((0, 0), (r, r), (r, r), (0, 0)))


def _img_spec(shape3):
    """BlockSpec for one image per grid step over leading dim."""
    n = len(shape3)
    return pl.BlockSpec((1,) + shape3,
                        lambda i, _n=n: (i,) + (0,) * _n)


def _call_filter(x, kt, ksize, dilation, interpret):
    b, h, w, c = x.shape
    r = dilation * (ksize // 2)
    taps = _taps(ksize, dilation)
    xp = _pad_hw(x, r)
    return pl.pallas_call(
        partial(_fwd_kernel, taps=taps, h=h, w=w),
        grid=(b,),
        in_specs=[_img_spec(xp.shape[1:]), _img_spec(kt.shape[1:])],
        out_specs=_img_spec((h, w, c)),
        out_shape=jax.ShapeDtypeStruct((b, h, w, c), x.dtype),
        cost_estimate=pl.CostEstimate(
            flops=2 * b * h * w * c * len(taps), transcendentals=0,
            bytes_accessed=(2 * x.size + kt.size) * 4),
        interpret=interpret,
        compiler_params=_compiler_params(),
    )(xp, kt)


@partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _dlf(x, kt, ksize, dilation, interpret):
    return _call_filter(x, kt, ksize, dilation, interpret)


def _dlf_fwd(x, kt, ksize, dilation, interpret):
    return _call_filter(x, kt, ksize, dilation, interpret), (x, kt)


def _dlf_bwd(ksize, dilation, interpret, res, g):
    x, kt = res
    b, h, w, c = x.shape
    t = ksize * ksize
    r = dilation * (ksize // 2)
    taps = _taps(ksize, dilation)

    gp = _pad_hw(g, r)
    ktp = jnp.pad(kt, ((0, 0), (0, 0), (r, r), (r, r)))
    dx = pl.pallas_call(
        partial(_dx_kernel, taps=taps, h=h, w=w, r2=2 * r),
        grid=(b,),
        in_specs=[_img_spec(gp.shape[1:]), _img_spec(ktp.shape[1:])],
        out_specs=_img_spec((h, w, c)),
        out_shape=jax.ShapeDtypeStruct((b, h, w, c), x.dtype),
        interpret=interpret,
        compiler_params=_compiler_params(),
    )(gp, ktp)

    xp = _pad_hw(x, r)
    dk = pl.pallas_call(
        partial(_dk_kernel, taps=taps, h=h, w=w),
        grid=(b,),
        in_specs=[_img_spec(xp.shape[1:]), _img_spec((h, w, c))],
        out_specs=_img_spec((t, h, w)),
        out_shape=jax.ShapeDtypeStruct((b, t, h, w), jnp.float32),
        interpret=interpret,
        compiler_params=_compiler_params(),
    )(xp, g)
    return dx, dk


_dlf.defvjp(_dlf_fwd, _dlf_bwd)


def fused_dynamic_filter_available(shape, ksize: int,
                                   dilation: int = 1) -> bool:
    """True when one grid step's tiles fit the kernel's VMEM budget.
    Counts BOTH the padded x/cotangent tile (C channels) and the
    tap-major kernel-map tile (ksize² planes) — the backward loads the
    padded kernel maps too, which dominate at low channel counts."""
    _, h, w, c = shape
    r = dilation * (ksize // 2)
    return ((h + 2 * r) * (w + 2 * r) * (c + ksize * ksize)
            <= _MAX_TILE_ELEMS)


def fused_dynamic_filter(x: jnp.ndarray, kernels: jnp.ndarray, ksize: int,
                         dilation: int = 1,
                         interpret: bool | None = None) -> jnp.ndarray:
    """Drop-in for ``models.hdfnet.dynamic_local_filter`` (same
    signature/semantics: x [B,H,W,C], kernels [B,H,W,ksize²], SAME
    zero padding, channel-shared spatial kernels).  Differentiable via
    the Pallas backward kernels; ``interpret`` defaults to auto
    (interpret on CPU, Mosaic on TPU).  Oversize inputs fall back to
    the XLA im2col path."""
    b, h, w, c = x.shape
    if kernels.shape != (b, h, w, ksize * ksize):
        raise ValueError(
            f"kernels shape {kernels.shape} != {(b, h, w, ksize * ksize)}")
    if ksize % 2 == 0:
        raise ValueError(f"ksize must be odd, got {ksize}")
    if not fused_dynamic_filter_available(x.shape, ksize, dilation):
        from ..models.hdfnet import dynamic_local_filter

        return dynamic_local_filter(x, kernels, ksize, dilation,
                                    impl="xla")
    # Tap-major [B, T, H, W]: one clean (H, W) lane tile per tap.
    kt = jnp.moveaxis(kernels, -1, 1).astype(jnp.float32)
    return _dlf(x, kt, ksize, dilation, _interpret(interpret))
