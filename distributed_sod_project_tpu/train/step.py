"""Shared step-building blocks + the compiled eval step.

The reference's inner loop (SURVEY.md §3.1: H2D copy → cuDNN forward →
loss → backward with DDP's bucketed NCCL allreduce → SGD step) compiles
to ONE XLA program per step — built by the rules engine's unified step
builder (parallel/engine.py, the only train-step builder since the
round-18 legacy deletion).  This module keeps the pieces every preset
shares — remat policy resolution, the optimizer/EMA tail
(``apply_update``), step chunking (``chunked_step_fn``), multi-scale
resize, health metrics — plus the forward-only eval step.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .state import TrainState
from ..utils.compat import shard_map


def resolve_remat_policy(name: str):
    """model.remat_policy → a jax.checkpoint policy.  "none" recomputes
    everything; "dots" saves matmul/conv outputs (recompute only
    elementwise — the usual FLOPs/HBM sweet spot on the MXU);
    "dots_no_batch" saves only batch-free contractions."""
    policies = {
        "none": None,  # jax.checkpoint default: nothing saveable
        "dots": jax.checkpoint_policies.dots_saveable,
        "dots_no_batch":
            jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
    }
    if name not in policies:
        raise ValueError(
            f"model.remat_policy must be one of {sorted(policies)}, "
            f"got {name!r}")
    return policies[name]


def maybe_remat(fn, remat: bool, remat_policy: str):
    """The one remat wrap shared by the DP/SP/TP step builders: resolve
    the policy EAGERLY (a typo'd policy name fails at build time, even
    with remat off) and checkpoint ``fn`` when remat is on."""
    policy = resolve_remat_policy(remat_policy)
    return jax.checkpoint(fn, policy=policy) if remat else fn


def _loss_kwargs(loss_cfg) -> Dict[str, Any]:
    return dict(
        bce_w=loss_cfg.bce,
        iou_w=loss_cfg.iou,
        ssim_w=loss_cfg.ssim,
        cel_w=loss_cfg.cel,
        ssim_window=loss_cfg.ssim_window,
        fused=loss_cfg.fused_kernel,
    )


def apply_update(state: TrainState, grads, new_stats, tx, *,
                 ema_decay: float = 0.0):
    """Shared optimizer/EMA tail of every train step (DP and TP).

    The EMA blends only on micro-steps where the parameters actually
    changed — derived by comparing trees, not by counting steps, so it
    stays correct under ``optax.MultiSteps`` accumulation AND
    ``apply_if_finite`` skips (a step counter desyncs the moment one
    micro-step is rejected).  Effective per-update decay is therefore
    exactly ``ema_decay``.
    """
    updates, new_opt = tx.update(grads, state.opt_state, state.params)
    new_params = optax.apply_updates(state.params, updates)
    new_ema = state.ema_params
    if ema_decay and new_ema is not None:
        d = jnp.float32(ema_decay)
        applied = jnp.any(jnp.stack([
            jnp.any(a != b) for a, b in zip(
                jax.tree_util.tree_leaves(state.params),
                jax.tree_util.tree_leaves(new_params))]))
        new_ema = jax.tree_util.tree_map(
            lambda e, p: jnp.where(
                applied, e * d + p.astype(e.dtype) * (1.0 - d), e),
            new_ema, new_params)
    # replace() (not a fresh TrainState) so fields this tail does not
    # touch — the int8_ef comm_residual — ride through unchanged.
    return state.replace(
        step=state.step + 1,
        params=new_params,
        batch_stats=new_stats,
        opt_state=new_opt,
        ema_params=new_ema,
    )


def notfinite_count(opt_state) -> Optional[jnp.ndarray]:
    """The ``apply_if_finite`` consecutive-failure counter, when the
    optimizer is wrapped with ``optim.skip_nonfinite`` (it is the
    OUTERMOST transform, so the counter sits at the state root);
    None otherwise."""
    if hasattr(opt_state, "notfinite_count"):
        return opt_state.notfinite_count
    return None


def chunked_step_fn(step_fn, steps_per_dispatch: int, *,
                    always_scan: bool = False):
    """Fold ``steps_per_dispatch`` train steps into ONE program body:
    a ``lax.scan`` of ``step_fn`` over batches stacked along a new
    leading axis, returning the final carry and the per-step metrics
    stacked along that same axis.

    Shared by all three step builders (DP shard_map, GSPMD TP, SP) so
    the chunking transform cannot diverge between them.  With k == 1
    the step function is returned UNTOUCHED (no scan wrapper) — the
    historical per-step program replays bit-identically — unless
    ``always_scan`` asks for the degenerate 1-step scan, which exists
    for the bitwise k-equivalence suite: scan(k) vs k dispatches of
    scan(1) is the comparison XLA:CPU keeps bitwise (the plain-vs-scan
    residual is a while-body conv-canonicalization layout artifact,
    quantified in tests/test_step_chunking.py).

    Because the per-step RNG folds on ``state.step`` INSIDE ``step_fn``
    and the carry threads the real TrainState, each scan iteration is
    the exact computation the sequential dispatch would run — per-step
    dropout draws, LR schedule reads, EMA gating and the
    ``apply_if_finite`` failure counter all advance identically.
    """
    k = int(steps_per_dispatch)
    if k < 1:
        raise ValueError(f"steps_per_dispatch must be >= 1, got {k}")
    if k == 1 and not always_scan:
        return step_fn

    def chunk_fn(state, batches):
        return lax.scan(step_fn, state, batches, length=k)

    return chunk_fn


def chunk_batch_spec(base_spec: P) -> P:
    """Batch PartitionSpec for a stacked chunk: the new leading k axis
    is unsharded (every device runs all k steps), the original batch
    dims keep their sharding shifted one dim right."""
    return P(None, *base_spec)


def rescale_batch(batch, scale_hw):
    """On-device multi-scale resize (image/mask/depth → ``scale_hw``);
    shared by the shard_map and GSPMD steps."""
    hw = batch["image"].shape[1:3]
    if scale_hw is None or tuple(scale_hw) == tuple(hw):
        return batch
    out = dict(batch)
    for k in ("image", "mask", "depth"):
        if k in out:
            b, _, _, c = out[k].shape
            out[k] = jax.image.resize(
                out[k], (b,) + tuple(scale_hw) + (c,), "bilinear")
    return out


def maybe_health_metrics(metrics, params, grads, new_params,
                         health: bool):
    """Append the model-health numerics scalars (per-group grad norms,
    nonfinite provenance, update/weight ratio — utils/modelhealth.py)
    when ``health`` is on.  ONE helper shared by the DP/TP/SP step
    builders so the health surface cannot diverge between them; with
    the knob off the metric dict is returned untouched and the step
    program stays byte-for-byte the historical one."""
    if not health:
        return metrics
    from ..utils.modelhealth import health_step_metrics

    metrics.update(health_step_metrics(params, grads, new_params))
    return metrics


def make_eval_step(model, mesh: Mesh) -> Callable:
    """Build ``(state, batch) -> probs``: forward-only, running BN stats,
    sigmoid on the primary logit.  Output stays batch-sharded — the eval
    loop gathers per-host slices for metric accumulation."""

    def eval_fn(state: TrainState, batch):
        outs = model.apply(
            state.eval_variables(),
            batch["image"],
            batch.get("depth"),
            train=False,
        )
        return jax.nn.sigmoid(outs[0][..., 0].astype(jnp.float32))

    sharded = shard_map(
        eval_fn,
        mesh=mesh,
        in_specs=(P(), P("data")),
        out_specs=P("data"),
        check_vma=False,
    )
    return jax.jit(sharded)
