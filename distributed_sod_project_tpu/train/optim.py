"""Optimizer factory (SURVEY.md §2 C9).

SGD(momentum=0.9, nesterov, wd=5e-4) with poly decay is the reference
regime; AdamW is provided for the Swin config.  Weight decay is applied
as decoupled ``add_decayed_weights`` masked to exclude BatchNorm
scale/bias and conv biases (the reference's torch SGD decays everything;
masking norms is strictly better and standard for from-scratch runs).
"""

from __future__ import annotations

from typing import Tuple

import optax

from .schedules import build_schedule


def _decay_mask(params):
    """True for leaves that should receive weight decay: rank>=2 kernels
    (conv/dense); False for biases and norm scales (rank<=1)."""
    import jax

    return jax.tree_util.tree_map(lambda p: p.ndim >= 2, params)


def _path_layer_id(path, n_blocks: int) -> int:
    """Map a param path to its fine-tuning layer: 0 for the input
    embedding, i+1 for encoder block i, n_blocks+1 (top) for heads and
    everything else."""
    import re

    for entry in path:
        name = str(getattr(entry, "key", entry))
        if name in ("patch_embed", "pos_embed"):
            return 0
        m = re.fullmatch(r"block(\d+)", name)
        if m:
            return int(m.group(1)) + 1
    return n_blocks + 1


def scale_by_layer_decay(decay: float) -> optax.GradientTransformation:
    """Layer-wise LR decay (the standard transformer fine-tuning lever,
    ELECTRA/BEiT-style): updates for layer ``l`` scale by
    ``decay^(top - l)`` — heads train at full LR, the embedding at
    ``decay^(n_blocks+1)``.  Layers are inferred from the vit_sod
    param naming (``block{i}``, ``patch_embed``/``pos_embed``); params
    outside that naming train at full LR.  Trace-time path scan only —
    no runtime cost beyond one multiply per leaf."""
    import jax

    def init_fn(params):
        del params
        return optax.EmptyState()

    def update_fn(updates, state, params=None):
        del params
        # One flatten to find the deepest block: a path maps to layer
        # id <= n_blocks exactly when it IS embedding/block-scoped, so
        # _path_layer_id with a huge sentinel doubles as the scanner
        # (single definition of the block-naming convention).
        sentinel = 1 << 30
        leaves, _ = jax.tree_util.tree_flatten_with_path(updates)
        n_blocks = max((lid for path, _ in leaves
                        if (lid := _path_layer_id(path, sentinel))
                        <= sentinel), default=0)
        top = n_blocks + 1
        updates = jax.tree_util.tree_map_with_path(
            lambda path, u: u * (decay ** (top - _path_layer_id(path,
                                                                n_blocks))),
            updates)
        return updates, state

    return optax.GradientTransformation(init_fn, update_fn)


def build_optimizer(
    optim_cfg, total_steps: int
) -> Tuple[optax.GradientTransformation, optax.Schedule]:
    schedule = build_schedule(optim_cfg, total_steps)
    accum = getattr(optim_cfg, "accum_steps", 1) or 1
    # Under MultiSteps the inner count advances once per APPLIED update
    # (once per `accum` micro-steps — verified against optax source), so
    # the transform's schedule is re-indexed to keep decay on the
    # micro-step clock `total_steps` was sized in; the returned
    # `schedule` stays micro-step-indexed, so step.py's logged lr equals
    # the applied lr at every emit.
    tx_schedule = schedule if accum == 1 else (
        lambda count: schedule(count * accum))
    parts = []
    if optim_cfg.grad_clip_norm and optim_cfg.grad_clip_norm > 0:
        parts.append(optax.clip_by_global_norm(optim_cfg.grad_clip_norm))
    layer_decay = getattr(optim_cfg, "layer_decay", 1.0) or 1.0
    if optim_cfg.optimizer == "sgd":
        if optim_cfg.weight_decay:
            parts.append(
                optax.add_decayed_weights(optim_cfg.weight_decay, _decay_mask)
            )
        if optim_cfg.momentum:
            parts.append(
                optax.trace(
                    decay=optim_cfg.momentum, nesterov=optim_cfg.nesterov
                )
            )
        if layer_decay != 1.0:
            parts.append(scale_by_layer_decay(layer_decay))
        parts.append(optax.scale_by_learning_rate(tx_schedule))
    elif optim_cfg.optimizer == "adamw":
        parts.append(optax.scale_by_adam())
        if optim_cfg.weight_decay:
            parts.append(
                optax.add_decayed_weights(optim_cfg.weight_decay, _decay_mask)
            )
        if layer_decay != 1.0:
            parts.append(scale_by_layer_decay(layer_decay))
        parts.append(optax.scale_by_learning_rate(tx_schedule))
    elif optim_cfg.optimizer == "lars":
        if layer_decay != 1.0:
            raise ValueError(
                "optim.layer_decay is for transformer fine-tuning "
                "(adamw/sgd); lars already adapts rates per layer")
        # Layer-wise adaptive rates for large-batch DP scaling
        # (PAPERS.md: efficient large-scale ConvNet training lineage) —
        # the standard remedy when pod-scale global batches stall plain
        # SGD.  optax.lars is a complete transformation (includes wd,
        # momentum and the lr), so it absorbs the whole chain tail; any
        # grad-clip part already in `parts` stays in front.
        # trust_ratio_mask: standard LARS adapts only rank>=2 kernels —
        # biases/norm affines keep plain SGD steps (the default True
        # would scale their updates by ~||b||·1e-3, freezing them).
        parts.append(optax.lars(
            learning_rate=tx_schedule,
            weight_decay=optim_cfg.weight_decay,
            weight_decay_mask=_decay_mask,
            trust_ratio_mask=_decay_mask,
            momentum=optim_cfg.momentum,
            nesterov=optim_cfg.nesterov,
        ))
    else:
        raise ValueError(f"unknown optimizer {optim_cfg.optimizer!r}")
    tx = optax.chain(*parts)
    if accum > 1:
        # Micro-batch accumulation: the update applies every `accum`
        # micro-steps; between them gradients average in MultiSteps
        # state.  The per-chip batch can then shrink by `accum` at
        # equal effective batch — the memory lever when remat alone is
        # not enough.
        tx = optax.MultiSteps(tx, every_k_schedule=accum)
    skip = getattr(optim_cfg, "skip_nonfinite", 0) or 0
    if skip > 0:
        # Outermost so a non-finite micro-gradient never reaches the
        # MultiSteps accumulator: the whole micro-step becomes a no-op
        # (the DDP-era alternative was a poisoned replica bringing down
        # the run).  max_consecutive_errors is effectively infinite
        # because optax's semantics past the threshold are to ACCEPT the
        # bad update — the opposite of what anyone wants; instead the
        # train loop watches the in-state notfinite counter (surfaced as
        # the `notfinite_count` metric) and raises once it exceeds the
        # configured limit.
        tx = optax.apply_if_finite(tx, max_consecutive_errors=10**9)
    return tx, schedule
