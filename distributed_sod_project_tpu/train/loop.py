"""The training engine: config → trained checkpoints (SURVEY.md §2 C1, §3.1).

``fit(cfg)`` is the whole reference ``train.py::main`` (SURVEY.md §3.1)
minus process spawning: on TPU pods every host runs the same ``fit``
under ``jax.distributed`` and the mesh spans all chips; there is no
torchrun/fork step.  Per step the host only feeds its local shard of the
batch and reads back scalar metrics — everything else (forward, loss,
backward, cross-replica psum, optimizer) is one compiled XLA program
(`make_train_step`).
"""

from __future__ import annotations

import time
from typing import Callable, Dict, Optional

import jax
import numpy as np

from ..ckpt import CheckpointManager
from ..configs.base import ExperimentConfig
from ..data import HostDataLoader, prefetch_to_device, resolve_dataset
from ..models import build_model
from ..parallel.mesh import make_mesh, replicated_sharding
from ..utils.logging import get_logger, is_primary_process
from ..utils.timing import StepTimer
from .optim import build_optimizer
from .state import create_train_state, param_count
from .step import make_eval_step, make_train_step


def fit(
    cfg: ExperimentConfig,
    workdir: Optional[str] = None,
    resume: bool = False,
    max_steps: Optional[int] = None,
    hooks: Optional[Dict[str, Callable]] = None,
) -> Dict[str, float]:
    """Run the full training loop; returns final scalar metrics.

    ``max_steps`` truncates (smoke tests / benchmarks); ``hooks`` may
    contain ``on_metrics(step, dict)`` for test instrumentation.
    """
    log = get_logger()
    hooks = hooks or {}
    workdir = workdir or cfg.checkpoint_dir

    mesh = make_mesh(cfg.mesh)
    n_dev = mesh.devices.size
    if cfg.global_batch_size % n_dev:
        raise ValueError(
            f"global_batch_size={cfg.global_batch_size} not divisible by "
            f"mesh size {n_dev}")

    dataset = resolve_dataset(cfg.data)
    loader = HostDataLoader(
        dataset,
        global_batch_size=cfg.global_batch_size,
        shard_id=jax.process_index(),
        num_shards=jax.process_count(),
        shuffle=True,
        seed=cfg.seed,
        hflip=cfg.data.hflip,
        num_workers=cfg.data.num_workers,
    )
    steps_per_epoch = cfg.steps_per_epoch or loader.steps_per_epoch
    if steps_per_epoch <= 0:
        raise ValueError(
            f"dataset of {len(dataset)} samples yields zero steps at "
            f"global_batch_size={cfg.global_batch_size}")
    total_steps = steps_per_epoch * cfg.num_epochs
    if max_steps is not None:
        total_steps = min(total_steps, max_steps)

    model = build_model(cfg.model)
    tx, schedule = build_optimizer(cfg.optim, total_steps)

    sample = next(iter(loader))
    state = create_train_state(jax.random.key(cfg.seed), model, tx, sample,
                               pretrained=cfg.model.pretrained)
    log.info("model=%s params=%.2fM devices=%d global_batch=%d "
             "steps/epoch=%d total_steps=%d",
             cfg.model.name, param_count(state) / 1e6, n_dev,
             cfg.global_batch_size, steps_per_epoch, total_steps)

    mgr = CheckpointManager(workdir, keep=cfg.keep_checkpoints)
    if is_primary_process():
        mgr.save_config(cfg)
    start_step = 0
    if resume:
        ck_step = mgr.latest_step()
        if ck_step is not None:
            state = mgr.restore(state, ck_step)
            start_step = int(state.step)
            log.info("resumed from checkpoint step %d", start_step)

    state = jax.device_put(state, replicated_sharding(mesh))
    train_step = make_train_step(model, cfg.loss, tx, mesh, schedule=schedule)

    timer = StepTimer()
    last_metrics: Dict[str, float] = {}
    step = start_step
    last_saved = -1
    try:
        for epoch in range(start_step // max(steps_per_epoch, 1), cfg.num_epochs):
            loader.set_epoch(epoch)
            # mesh= (not sharding=): each host contributes its local
            # slice of the global batch — correct on multi-host pods.
            it = prefetch_to_device(
                iter(loader), size=cfg.data.prefetch_batches, mesh=mesh)
            for batch in it:
                if step >= total_steps:
                    break
                state, metrics = train_step(state, batch)
                step += 1
                timer.tick()
                if step % cfg.log_every_steps == 0 or step == total_steps:
                    host = {k: float(v) for k, v in metrics.items()}
                    host["imgs_per_sec"] = timer.images_per_sec(
                        cfg.global_batch_size)
                    host["epoch"] = epoch
                    last_metrics = host
                    if is_primary_process():
                        log.info(
                            "step %d/%d  loss=%.4f  lr=%.2e  %.1f imgs/s",
                            step, total_steps, host.get("total", float("nan")),
                            host.get("lr", float("nan")),
                            host["imgs_per_sec"])
                    if "on_metrics" in hooks:
                        hooks["on_metrics"](step, host)
                if cfg.checkpoint_every_steps and (
                        step % cfg.checkpoint_every_steps == 0):
                    # state passed as-is: orbax's async save does the D2H
                    # copy behind the next train steps (no device_get stall).
                    mgr.save(step, state)
                    last_saved = step
            if step >= total_steps:
                break
        if step != last_saved:
            mgr.save(step, state, force=True)
    finally:
        mgr.close()
    last_metrics["final_step"] = step
    return last_metrics
