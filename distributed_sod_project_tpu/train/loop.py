"""The training engine: config → trained checkpoints (SURVEY.md §2 C1, §3.1).

``fit(cfg)`` is the whole reference ``train.py::main`` (SURVEY.md §3.1)
minus process spawning: on TPU pods every host runs the same ``fit``
under ``jax.distributed`` and the mesh spans all chips; there is no
torchrun/fork step.  Per step the host only feeds its local shard of the
batch and reads back scalar metrics — everything else (forward, loss,
backward, cross-replica psum, optimizer) is one compiled XLA program
built by the unified rules engine (`parallel/engine.py`).
"""

from __future__ import annotations

import time
from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..ckpt import CheckpointManager
from ..configs.base import ExperimentConfig
from ..data import prefetch_to_device, resolve_dataset
from ..models import build_model
from ..parallel.mesh import make_mesh, replicated_sharding
from ..utils.logging import get_logger, is_primary_process
from ..utils.timing import StepTimer
from .optim import build_optimizer
from .state import create_train_state, param_count
from .step import make_eval_step


def _poll_stop(guard, step: int, sync_every: int) -> bool:
    """Graceful-stop polling cadence (one knob, unit-tested):
    single-process reads the host-local flag every step; multi-host
    agrees only at deterministic steps (all hosts must enter the
    allgather together — ``sync_every`` = the logging cadence), keeping
    the async run-ahead between agreement points."""
    if jax.process_count() == 1:
        return guard.should_stop
    if step % sync_every == 0:
        # Blocking allgather — throttled so the host keeps
        # its async run-ahead between agreement points.
        return guard.sync()
    return False


def fit(
    cfg: ExperimentConfig,
    workdir: Optional[str] = None,
    resume: bool = False,
    max_steps: Optional[int] = None,
    hooks: Optional[Dict[str, Callable]] = None,
    profile_dir: Optional[str] = None,
    telemetry_port: Optional[int] = None,
    telemetry_port_file: Optional[str] = None,
) -> Dict[str, float]:
    """Run the full training loop; returns final scalar metrics.

    ``max_steps`` truncates (smoke tests / benchmarks); ``hooks`` may
    contain ``on_metrics(step, dict)`` and — under step chunking —
    ``on_chunk_metrics(step, stacked_dict)`` for test instrumentation;
    ``profile_dir`` captures a jax.profiler trace of a short post-warmup
    step window (view in TensorBoard/Perfetto).

    ``telemetry_port`` (overrides ``cfg.telemetry_port``; >= 0 = on,
    0 = ephemeral) starts the opt-in telemetry sidecar
    (utils/telemetry.py — /metrics, /healthz off the step watchdog,
    /debug/traces, on-demand /debug/profile), publishing the bound
    port atomically to ``telemetry_port_file``.  ``cfg.trace_sample``
    additionally records per-chunk span timelines
    (docs/OBSERVABILITY.md).

    ``cfg.steps_per_dispatch=k > 1`` folds k steps into one
    ``lax.scan`` dispatch: the loop advances chunk-by-chunk (every
    cadence knob must divide by k — validate_steps_per_dispatch), k
    host batches stack into one H2D transfer, and the steady state
    does exactly ONE host↔device sync per chunk (the stacked-metrics
    readback).  See docs/PERFORMANCE.md "Device-side step chunking".

    Resilience (docs/RESILIENCE.md): restore lands on the newest VALID
    checkpoint; ``cfg.watchdog_deadline_s`` arms the wedged-step
    watchdog; ``cfg.data.skip_budget`` tolerates corrupt samples;
    ``DSOD_FAULTS`` injects deterministic faults (chaos tests).
    """
    import os

    from ..resilience import inject
    from ..utils.observability import (MetricWriter, PreemptionGuard,
                                       profile_window)

    log = get_logger()
    hooks = hooks or {}
    workdir = workdir or cfg.checkpoint_dir
    plan = inject.plan_from_env()

    if not cfg.health_numerics:
        # Loudness: both knobs only act through the numerics monitor —
        # set without it they would be silent no-ops, and an operator
        # who opted into rollback protection must not run unprotected.
        if cfg.health_rollback_hint:
            raise ValueError(
                "health_rollback_hint=true requires health_numerics=true "
                "(the rollback hand-off consumes the numerics alerts)")
        if cfg.health_alert_rules:
            raise ValueError(
                "health_alert_rules set but health_numerics is false — "
                "the training alert engine only runs with the numerics "
                "telemetry on")

    # Device-side step chunking (docs/PERFORMANCE.md): k steps fold
    # into one lax.scan dispatch and the loop advances chunk-by-chunk.
    # Fault plans force k=1 — poison/stall/SIGTERM are PER-STEP
    # semantics the chaos suite asserts exactly, and a scanned chunk
    # has no host boundary between its steps to inject at.
    k = int(cfg.steps_per_dispatch)
    if plan is not None and k > 1:
        log.warning(
            "DSOD_FAULTS is set: forcing steps_per_dispatch=1 (was %d) "
            "so per-step poison/stall/SIGTERM semantics stay exact", k)
        k = 1

    mesh = make_mesh(cfg.mesh)
    n_dev = mesh.devices.size
    # The batch dim only shards over ``data`` (model/seq shard other
    # dims), so that is the divisibility requirement.
    data_size = mesh.shape.get("data", n_dev)
    if cfg.global_batch_size % data_size:
        raise ValueError(
            f"global_batch_size={cfg.global_batch_size} not divisible by "
            f"the data mesh axis ({data_size})")

    from ..data.tfdata import make_loader

    from ..parallel.mesh import host_batch_shard

    # Mesh-position-derived, NOT process_index: hosts that share a
    # data block (a seq/model axis spanning processes) must load
    # IDENTICAL batches — their devices hold different shards of the
    # same images.  Pure DP reduces to (process_index, process_count).
    shard_id, num_shards = host_batch_shard(mesh)
    dataset = resolve_dataset(cfg.data)
    # Corrupt-sample degradation: bounded skip-budget with
    # deterministic substitution instead of an epoch-killing exception
    # (host/grain backends fetch through the wrapper; tfdata enforces
    # the same budget via its shortfall check — see dataguard.py).
    data_guard = None
    if cfg.data.skip_budget > 0 or (plan is not None
                                    and plan.corrupt_indices):
        from ..resilience.dataguard import GuardedDataset

        data_guard = GuardedDataset(dataset, cfg.data.skip_budget,
                                    fault_plan=plan)
        dataset = data_guard
    # Host-data-plane telemetry: every blocking point in the loader /
    # prefetch stages reports here; the per-interval deltas ride the
    # metric stream (data_starved_ms is the input-bound signal).
    from ..utils.observability import PipelineStats

    data_stats = PipelineStats()
    # Chunk tracing (utils/tracing.py; docs/OBSERVABILITY.md): sampled
    # chunks record data_wait/dispatch/flush (+ckpt/eval, + synthetic
    # build/ring-wait/h2d children from the data-plane counters)
    # correlated to step numbers.  sample=0 (default): no clock reads.
    from ..utils.tracing import Tracer, mint_trace_id

    tracer = Tracer(sample=cfg.trace_sample)
    loader = make_loader(
        dataset, cfg.data,
        global_batch_size=cfg.global_batch_size,
        shard_id=shard_id,
        num_shards=num_shards,
        shuffle=True,
        seed=cfg.seed,
        hflip=cfg.data.hflip,
        rotate_degrees=cfg.data.rotate_degrees,
        color_jitter=cfg.data.color_jitter,
        num_workers=cfg.data.num_workers,
        skip_budget=cfg.data.skip_budget,
        stats=data_stats,
    )
    steps_per_epoch = cfg.steps_per_epoch or loader.steps_per_epoch
    if steps_per_epoch <= 0:
        raise ValueError(
            f"dataset of {len(dataset)} samples yields zero steps at "
            f"global_batch_size={cfg.global_batch_size}")
    # Chunk-boundary contract: every cadence knob AND the loader's
    # actual epoch period must be multiples of k (loud ValueError
    # naming the offending pair — configs/base.py).
    from ..configs.base import validate_steps_per_dispatch

    validate_steps_per_dispatch(cfg.replace(steps_per_dispatch=k),
                                loader.steps_per_epoch)
    total_steps = steps_per_epoch * cfg.num_epochs
    if max_steps is not None:
        total_steps = min(total_steps, max_steps)
        if k > 1 and total_steps % k:
            raise ValueError(
                f"max_steps={max_steps} truncates the run to "
                f"{total_steps} steps, not a multiple of "
                f"steps_per_dispatch={k} — the loop would overshoot "
                "mid-chunk; pass a max_steps that is a multiple of k")

    model = build_model(cfg.model)
    tx, schedule = build_optimizer(cfg.optim, total_steps)

    sample = next(iter(loader))
    from ..utils.checks import validate_batch

    validate_batch(sample, cfg.data.image_size, use_depth=cfg.data.use_depth)
    state = create_train_state(jax.random.key(cfg.seed), model, tx, sample,
                               pretrained=cfg.model.pretrained,
                               ema=cfg.optim.ema_decay > 0)
    # Training numerics telemetry (utils/modelhealth.py;
    # docs/OBSERVABILITY.md "Model health"): the step emits per-group
    # grad norms / nonfinite provenance / update ratio, the monitor
    # aggregates them for the sidecar, and the alert engine watches the
    # derived signals.  All None when the knob is off — every touch
    # below guards on that, so the default path pays nothing.
    # Flight recorder (utils/flightrecorder.py): constructed AFTER the
    # telemetry registry below; the alert engines built first hook
    # their transitions through this cell so construction order stays
    # linear.  None-when-off discipline throughout.
    _recorder_cell = [None]

    def _rec_transition(rule, old, new, snap):
        if _recorder_cell[0] is not None:
            _recorder_cell[0].alert_transition(rule, old, new, snap)

    health_monitor = None
    health_alerts = None
    if cfg.health_numerics:
        from ..utils.alerts import AlertEngine, parse_rules
        from ..utils.modelhealth import (HealthMonitor,
                                         default_numerics_rules,
                                         param_group_names)

        health_monitor = HealthMonitor(param_group_names(state.params))
        health_alerts = AlertEngine(
            default_numerics_rules(clear_s=cfg.health_alert_clear_s)
            + parse_rules(cfg.health_alert_rules),
            on_transition=_rec_transition)

    # Capacity ledger + goodput SLO (utils/capacity.py, utils/slo.py;
    # docs/OBSERVABILITY.md "Capacity & SLO").  Both None when off —
    # every touch below guards, so the default loop pays nothing and
    # the sidecar surface is byte-identical.
    capacity = None
    slo_tracker = None
    t_run0 = time.monotonic()
    if cfg.capacity_ledger:
        from ..utils.capacity import CapacityLedger

        def _train_shares():
            # Host-vs-device attribution for the train loop: the
            # starved counter is exactly "device idle waiting on the
            # host data plane" — the futile-to-scale share.
            wall_ms = max((time.monotonic() - t_run0) * 1000.0, 1e-9)
            starved = data_stats.snapshot().get("data_starved_ms", 0.0)
            host = min(starved / wall_ms, 1.0)
            return {"device": max(1.0 - host, 0.0), "queue": 0.0,
                    "host": host}

        capacity = CapacityLedger(share_fn=_train_shares)
    if cfg.slo_objectives:
        from ..utils.slo import build_tracker

        slo_tracker = build_tracker(
            cfg.slo_objectives, burn_threshold=cfg.slo_burn_threshold,
            alert_for_s=cfg.slo_alert_for_s,
            alert_clear_s=cfg.slo_alert_clear_s,
            on_transition=_rec_transition)

    def _observe_health(metrics_host) -> None:
        """Feed one fetched metric dict to the health monitor + alert
        engine.  Under ``health_rollback_hint`` a FIRING rollback-
        hinted alert (numerics_nonfinite) raises the divergence
        RuntimeError the PR-1 supervisor's rollback-and-retry policy
        recognizes (resilience/supervisor.py::is_divergence)."""
        if health_monitor is None:
            return
        health_monitor.observe(metrics_host)
        sigs, details = health_monitor.signals()
        health_alerts.evaluate(sigs, details=details)
        if cfg.health_rollback_hint:
            fired = health_alerts.firing(hint="rollback")
            if fired:
                snap = health_monitor.snapshot()
                raise RuntimeError(
                    f"model-health alert {fired[0].name!r} "
                    f"(first non-finite group: "
                    f"{snap['last_nonfinite_group'] or '?'}): non-finite "
                    "gradient updates detected — rolling back to the "
                    "last checkpoint (health_rollback_hint)")

    log.info("model=%s params=%.2fM devices=%d global_batch=%d "
             "steps/epoch=%d total_steps=%d",
             cfg.model.name, param_count(state) / 1e6, n_dev,
             cfg.global_batch_size, steps_per_epoch, total_steps)

    if cfg.best_metric and not cfg.eval_every_steps:
        raise ValueError(
            "best_metric retention needs eval_every_steps > 0 — without "
            "eval metrics orbax never deletes checkpoints and keep_"
            "checkpoints is silently ignored")
    if cfg.best_mode not in ("max", "min"):
        raise ValueError(f"best_mode must be max|min, got {cfg.best_mode!r}")
    mgr = CheckpointManager(workdir, keep=cfg.keep_checkpoints,
                            best_metric=cfg.best_metric,
                            best_mode=cfg.best_mode)
    if is_primary_process():
        mgr.save_config(cfg)
    start_step = 0
    resumed_from = -1
    if resume:
        # Newest VALID checkpoint: tmp/truncated/corrupt step dirs are
        # quarantined and the next-newest is tried (ckpt/manager.py) —
        # a preemption mid-save costs checkpoint_every_steps of
        # recompute, never the run.
        state, ck_step = mgr.restore_latest_valid(state)
        if ck_step is not None:
            start_step = int(state.step)
            resumed_from = start_step
            log.info("resumed from checkpoint step %d", start_step)
            if k > 1 and start_step % k:
                # checkpoint_every_steps % k == 0 guarantees chunk-
                # aligned saves, so a misaligned resume means the
                # checkpoint came from a run with a different k (e.g. a
                # k=1 final force-save mid-cycle).
                raise ValueError(
                    f"resumed checkpoint step {start_step} is not a "
                    f"multiple of steps_per_dispatch={k} — the chunked "
                    "loop must re-enter on a chunk boundary.  Resume "
                    "with steps_per_dispatch=1 (or a k dividing "
                    f"{start_step}) until the next aligned checkpoint")

    # Step builder: every preset routes through the unified rule-driven
    # builder (parallel/engine.py — the only step builder since the
    # round-18 legacy deletion): shard_map DP for the CNN zoo
    # (named-axis SyncBN), GSPMD tp/fsdp when the model axis is
    # sharded, any ZeRO level is on, or parallel.preset=fsdp shards the
    # params themselves, and the sequence-parallel preset when ``seq``
    # is sharded (ring attention over token blocks, vit_sod only).
    from ..configs.base import validate_parallel

    validate_parallel(cfg)
    from ..parallel import engine as engine_mod

    zero_eff = engine_mod.effective_zero(cfg)
    preset = engine_mod.select_preset(cfg, mesh)
    use_sp = preset == "sp"
    use_gspmd = preset in ("tp", "fsdp")
    if use_sp:
        if (mesh.shape.get("model", 1) > 1 or cfg.optim.zero1
                or cfg.parallel.zero > 0):
            raise ValueError(
                "mesh.seq>1 cannot combine with mesh.model>1 / "
                "optim.zero1 (pick one non-data axis per run)")
        if cfg.model.sync_bn:
            raise ValueError(
                "sequence parallelism requires a BatchNorm-free model: "
                "set model.sync_bn=false (use model.name=vit_sod)")
        if not hasattr(model, "patch"):
            raise ValueError(
                f"model {cfg.model.name!r} does not support sequence "
                "parallelism — only halo-free token models (vit_sod) "
                "shard over mesh.seq")
        if cfg.data.multiscale:
            raise ValueError(
                "data.multiscale is not supported with mesh.seq>1")
        seq = mesh.shape["seq"]
        rows = cfg.data.image_size[0] // model.patch
        if cfg.data.image_size[0] % model.patch or rows % seq:
            raise ValueError(
                f"image height {cfg.data.image_size[0]} must be a "
                f"multiple of patch*seq = {model.patch}*{seq}")
        state = jax.device_put(state, replicated_sharding(mesh))

        def step_factory(scale_hw):
            return engine_mod.make_unified_train_step(
                model, cfg.loss, tx, mesh, preset="sp",
                schedule=schedule, ema_decay=cfg.optim.ema_decay,
                donate_batch=True,
                sp_strategy=cfg.mesh.sp_strategy,
                remat=cfg.model.remat,
                remat_policy=cfg.model.remat_policy,
                steps_per_dispatch=k,
                health=cfg.health_numerics)
    elif use_gspmd:
        from ..parallel.rules import (PRESET_PARAM_RULES,
                                      fsdp_fallback_rule,
                                      shard_state_by_rules)

        if cfg.model.sync_bn:
            raise ValueError(
                "mesh.model>1 / optim.zero1 / parallel.preset=fsdp "
                "route through the GSPMD step, which has no named mesh "
                "axis: set model.sync_bn=false (BN stats are "
                "global-batch there, strictly stronger)")
        n_model = mesh.shape.get("model", 1)
        # Head-alignment guard — models exposing a scalar ``heads``
        # (vit_sod) promise boundary-aligned column shards; fail loudly
        # when the promise can't hold (GSPMD would re-gather q/k/v
        # every block).  Swin's head-major qkv packing aligns whenever
        # ``model`` divides a stage's head count (3,6,12,24) — only
        # non-dividing stages fall back to GSPMD resharding (see
        # parallel/tp.py docstring; stage 1 with model=2 is the one
        # case for Swin-T).
        heads = getattr(model, "heads", None)
        if n_model > 1 and isinstance(heads, int) and heads % n_model:
            raise ValueError(
                f"mesh.model={n_model} does not divide the model's "
                f"{heads} attention heads — pick a model-axis degree "
                "that divides the head count")
        if preset == "fsdp":
            state, state_shardings = shard_state_by_rules(
                state, mesh, rules=PRESET_PARAM_RULES["fsdp"],
                zero=zero_eff, fallback=fsdp_fallback_rule(mesh))
        else:
            state, state_shardings = shard_state_by_rules(
                state, mesh, zero=zero_eff)

        def step_factory(scale_hw):
            return engine_mod.make_unified_train_step(
                model, cfg.loss, tx, mesh, preset=preset,
                schedule=schedule, ema_decay=cfg.optim.ema_decay,
                scale_hw=scale_hw, donate_batch=True,
                remat=cfg.model.remat,
                remat_policy=cfg.model.remat_policy,
                steps_per_dispatch=k,
                health=cfg.health_numerics,
                state_shardings=state_shardings, zero=zero_eff)
    else:
        # Replicate first, THEN seed the residual — seeding places the
        # residual P('data'), which a blanket replicate would undo.
        residual = getattr(state, "comm_residual", None)
        state = jax.device_put(state.replace(comm_residual=None),
                               replicated_sharding(mesh))
        if cfg.parallel.grad_compression == "int8_ef":
            state = engine_mod.seed_comm_residual(
                state.replace(comm_residual=residual), mesh)

        def step_factory(scale_hw):
            return engine_mod.make_unified_train_step(
                model, cfg.loss, tx, mesh, preset="dp",
                schedule=schedule, remat=cfg.model.remat,
                ema_decay=cfg.optim.ema_decay,
                scale_hw=scale_hw, donate_batch=True,
                remat_policy=cfg.model.remat_policy,
                steps_per_dispatch=k,
                health=cfg.health_numerics,
                comm_bucket_mb=cfg.parallel.comm_bucket_mb,
                grad_compression=cfg.parallel.grad_compression,
                data_hosts=cfg.mesh.data_hosts)

    # Multi-scale training: one compiled step per size in the cycle
    # (each is a distinct static-shape XLA program; the resize happens
    # on-device inside the step).  Single-scale is the 1-entry cycle at
    # the loader's native (possibly non-square) image_size.
    ms_cycle = (tuple((s, s) for s in cfg.data.multiscale)
                or (tuple(cfg.data.image_size),))
    step_for_size = {
        hw: step_factory(None if hw == tuple(cfg.data.image_size) else hw)
        for hw in dict.fromkeys(ms_cycle)
    }
    # Multi-scale cycles per CHUNK (all k steps of a dispatch share one
    # static-shape program; each size stays its own compiled program).
    # At k=1 this reduces exactly to the historical per-step cycling.
    train_step_at = lambda i: step_for_size[ms_cycle[(i // k) % len(ms_cycle)]]  # noqa: E731

    # Capacity/SLO feed points (both no-ops when the knobs are off).
    # The ledger key names the static program (size × chunk factor);
    # observations are gated past the StepTimer's warmup so compile
    # time never poisons the EWMA the MFU gauge divides by.
    _cap_recorded = set()
    _cap_t_last = [None]

    def _cap_key(at_step: int) -> str:
        hw = ms_cycle[(at_step // k) % len(ms_cycle)]
        return f"train/{hw[0]}x{hw[1]}/k{k}"

    def _maybe_record_capacity(at_step, train_step, state, batch) -> None:
        if capacity is None:
            return
        ck = _cap_key(at_step)
        if ck not in _cap_recorded:
            _cap_recorded.add(ck)
            # One extra AOT compile per static shape, paid only with
            # the ledger opted in — the cost_analysis()/
            # memory_analysis() of the REAL step program.
            capacity.record_jit(ck, train_step, state, batch)
            # Comm ledger (ROADMAP item 4): the engine's static
            # shape-priced plan — per-collective bytes and link level,
            # overlap estimate, ZeRO/FSDP HBM saving — under the same
            # program key.  Guarded like every telemetry touch.
            try:
                capacity.record_comm(ck, engine_mod.comm_plan(
                    state, mesh, preset=preset, zero=zero_eff,
                    comm_bucket_mb=cfg.parallel.comm_bucket_mb,
                    grad_compression=cfg.parallel.grad_compression,
                    data_hosts=cfg.mesh.data_hosts))
            except Exception:  # noqa: BLE001 — telemetry only
                log.exception("capacity: comm_plan failed for %s", ck)

    def _observe_capacity_slo(chunk_start_step: int) -> None:
        """Per completed chunk: fold the measured per-step time into
        the ledger EWMA and feed one goodput SLO event per step."""
        if capacity is None and slo_tracker is None:
            return
        now = time.monotonic()
        prev, _cap_t_last[0] = _cap_t_last[0], now
        if prev is None or timer.ticks <= timer.warmup:
            return  # compile-time interval: not a measured step
        per_step_ms = (now - prev) * 1000.0 / k
        if capacity is not None:
            capacity.observe(_cap_key(chunk_start_step), per_step_ms)
        if slo_tracker is not None:
            slo_tracker.observe(True, latency_ms=per_step_ms,
                                model=cfg.model.name, n=k)

    # SP shards image rows over ``seq`` in addition to batch over
    # ``data``; every other path uses the default batch-only sharding.
    # Chunked batches carry a new leading k axis, unsharded.
    batch_spec_override = None
    if use_sp or k > 1:
        from jax.sharding import PartitionSpec as P

        sp_dims = ("data", "seq") if use_sp else ("data",)
        batch_spec_override = P(*(((None,) + sp_dims) if k > 1 else sp_dims))

    writer = MetricWriter(os.path.join(workdir, "tb")
                          if cfg.tensorboard else None)
    eval_fn = (_make_inline_eval(cfg, model, mesh)
               if cfg.eval_every_steps else None)

    # Wedged-dispatch watchdog: heartbeat fed by timer.tick() (one beat
    # per completed CHUNK — a dispatch is k steps, so the deadline
    # scales by k); a chunk past the deadline → stack dump + exit code
    # 114 for the supervising layer to re-fire (watchdog.py).
    watchdog = None
    if cfg.watchdog_deadline_s > 0:
        from ..resilience.watchdog import StepWatchdog

        on_stall = None
        if cfg.flight_recorder:
            from ..resilience.watchdog import WATCHDOG_EXIT_CODE

            def on_stall(msg):
                # The watchdog's exit-114 contract is exactly why the
                # recorder exists: snapshot the incident (guarded —
                # capture failing must not change the exit), THEN die
                # with the documented code.  Only installed with the
                # recorder armed; the default stall path is untouched.
                rec = _recorder_cell[0]
                if rec is not None:
                    rec.trigger("watchdog", msg[:200])
                    rec.stop()
                os._exit(WATCHDOG_EXIT_CODE)

        watchdog = StepWatchdog(
            cfg.watchdog_deadline_s * k,
            first_deadline_s=max(cfg.watchdog_compile_grace_s,
                                 cfg.watchdog_deadline_s * k),
            dump_dir=workdir, on_stall=on_stall,
        ).start()
    timer = StepTimer(on_tick=watchdog.beat if watchdog else None)
    last_metrics: Dict[str, float] = {}
    eval_metrics: Dict[str, float] = {}
    step = start_step
    # Opt-in telemetry sidecar: READS the objects above (stats, timer,
    # watchdog heartbeat, tracer, the live ``step``) over stdlib HTTP;
    # the loop's own behavior is identical with it on or off.  The
    # flight recorder samples the SAME registry onto disk, so it works
    # with the sidecar port off — durable history needs no socket.
    from ..utils.telemetry import (build_trainer_registry,
                                   build_trainer_telemetry)

    registry = None
    recorder = None
    eff_tport = cfg.telemetry_port if telemetry_port is None \
        else telemetry_port
    if cfg.flight_recorder or (eff_tport is not None and eff_tport >= 0):
        registry = build_trainer_registry(
            cfg, data_stats=data_stats, timer=timer, writer=writer,
            step_fn=lambda: step, tracer=tracer, health=health_monitor,
            alerts=health_alerts, capacity=capacity, slo=slo_tracker)
    if cfg.flight_recorder:
        import dataclasses as _dc

        from ..utils.flightrecorder import recorder_from_knobs

        recorder = recorder_from_knobs(
            cfg, dir_default=os.path.join(workdir, "flightrec"),
            families_fn=registry.prom_families,
            sections={
                "traces": lambda: tracer.snapshot(16),
                "alerts": lambda: (health_alerts.snapshot()
                                   if health_alerts is not None else {}),
                "slo": lambda: (slo_tracker.snapshot()
                                if slo_tracker is not None else {}),
                "capacity": lambda: (capacity.snapshot()
                                     if capacity is not None else {}),
                "health": lambda: (health_monitor.snapshot()
                                   if health_monitor is not None else {}),
                "last_metrics": lambda: dict(last_metrics),
                "config": lambda: _dc.asdict(cfg),
            },
            meta={"source": "trainer", "model": cfg.model.name,
                  "workdir": workdir})
        _recorder_cell[0] = recorder
        recorder.start()
    telemetry = build_trainer_telemetry(
        cfg, data_stats=data_stats, timer=timer, writer=writer,
        watchdog=watchdog, tracer=tracer, workdir=workdir,
        step_fn=lambda: step, port=telemetry_port,
        port_file=telemetry_port_file,
        health=health_monitor, alerts=health_alerts,
        capacity=capacity, slo=slo_tracker, registry=registry,
        recorder=recorder)
    # A restore means this step's checkpoint already exists on disk — a
    # zero-progress run must not force-save over it (orbax raises).
    last_saved = resumed_from
    last_eval_step = -1
    stop = False
    # Cross-host stop agreement only at deterministic steps (all hosts
    # must enter the collective together); local-only checks otherwise.
    sync_every = max(1, cfg.log_every_steps)
    profile_at = -1
    if profile_dir:
        profile_at = max(start_step, min(start_step + 10, total_steps - 1))
        # The loop only visits chunk-start steps; snap the profile
        # window onto one (exact historical value at k=1).
        profile_at -= (profile_at - start_step) % k
    # Resume position in LOADER coordinates: the loader always yields
    # loader.steps_per_epoch batches per epoch regardless of any
    # cfg.steps_per_epoch accounting override, so epoch/offset math must
    # use the loader's own period or the resumed stream diverges.
    loader_spe = max(loader.steps_per_epoch, 1)
    start_epoch = start_step // loader_spe
    if start_step % loader_spe and hasattr(loader, "skip_steps"):
        # Exact mid-epoch resume: the epoch order is a pure function of
        # (seed, epoch), so re-entry is an index skip — no replayed or
        # skipped samples vs the uninterrupted run.
        loader.skip_steps(start_step % loader_spe)
    # Epoch iteration is open-ended and bounded by total_steps (which
    # encodes cfg.num_epochs × steps_per_epoch): when cfg.steps_per_epoch
    # overrides the accounting, the loader may need more or fewer passes
    # than cfg.num_epochs.
    import itertools

    def _process_log(at_step, metrics_host, at_epoch):
        """The log-boundary block, shared by the k=1 inline path and the
        chunked flush.  Chunked metrics leaves are (k,)-stacked; the log
        line reports the chunk's LAST step — exactly the step a k=1 loop
        would log at this boundary."""
        nonlocal last_metrics
        host = {name: float(np.asarray(v).reshape(-1)[-1])
                for name, v in metrics_host.items()}
        if (cfg.optim.skip_nonfinite and
                host.get("notfinite_count", 0.0)
                >= cfg.optim.skip_nonfinite):
            raise RuntimeError(
                f"{int(host['notfinite_count'])} consecutive "
                "non-finite gradient updates (≥ optim."
                f"skip_nonfinite={cfg.optim.skip_nonfinite}) — "
                "training has diverged; no bad update was "
                "applied, restart from the last checkpoint "
                "with a lower lr / higher loss scale")
        host["imgs_per_sec"] = timer.images_per_sec(
            cfg.global_batch_size)
        host["epoch"] = at_epoch
        # Data-plane health for this logging interval:
        # data_starved_ms > 0 means the device waited on
        # the host pipeline (docs/PERFORMANCE.md).
        host.update(data_stats.delta())
        if cfg.data.skip_budget > 0:
            # Corrupt samples tolerated so far (dataguard
            # substitution + tfdata shortfall), surfaced as
            # a counter instead of an epoch-killing raise.
            host["data_skipped"] = float(
                (data_guard.skipped if data_guard is not None
                 else 0)
                + int(getattr(loader, "skipped", 0)))
        last_metrics = host
        writer.scalars(at_step, host)
        if is_primary_process():
            log.info(
                "step %d/%d  loss=%.4f  lr=%.2e  %.1f imgs/s",
                at_step, total_steps, host.get("total", float("nan")),
                host.get("lr", float("nan")),
                host["imgs_per_sec"])
        if "on_metrics" in hooks:
            hooks["on_metrics"](at_step, host)

    # One source for the "does this boundary read state?" predicates:
    # _run_state_events acts on them, _state_event_at (the chunked
    # loop's flush-ordering decision) ORs them — adding a state-reading
    # event means adding a predicate here, and both sides follow.
    def _eval_due(at_step) -> bool:
        return eval_fn is not None and at_step % cfg.eval_every_steps == 0

    def _ckpt_due(at_step) -> bool:
        return bool(cfg.checkpoint_every_steps
                    and at_step % cfg.checkpoint_every_steps == 0)

    def _run_state_events(at_step, trace=None):
        """Eval/checkpoint at a boundary — these read the CURRENT state,
        so under chunking they may only run while ``state`` still is the
        state at ``at_step`` (before the next chunk's donated dispatch
        replaces it).  ``trace`` (the boundary chunk's open trace dict)
        gets an eval/ckpt span per event."""
        nonlocal eval_metrics, last_eval_step, last_saved
        if _eval_due(at_step):
            t_e0 = time.monotonic() if trace else 0.0
            eval_metrics = eval_fn(state)
            if trace:
                tracer.record(trace["root"].trace_id, "eval", t_e0,
                              time.monotonic(),
                              parent_id=trace["root"].span_id,
                              attrs={"step": at_step})
            last_eval_step = at_step
            if recorder is not None:
                recorder.event("eval", step=at_step,
                               **{k: round(float(v), 6)
                                  for k, v in eval_metrics.items()})
            writer.scalars(at_step, {f"eval/{k}": v
                                     for k, v in eval_metrics.items()})
            if is_primary_process():
                log.info("eval @ %d: %s", at_step,
                         {k: round(v, 4) for k, v in
                          eval_metrics.items()})
            if watchdog is not None:
                # Inline eval is legitimate beat-free progress;
                # don't let a val sweep longer than the step
                # deadline read as a wedged dispatch.
                watchdog.beat(at_step, eval_metrics)
        if _ckpt_due(at_step):
            if (cfg.best_metric and eval_fn is not None
                    and last_eval_step != at_step):
                # best-k ranking must reflect THIS state, not a
                # stale measurement from an earlier step.
                eval_metrics = eval_fn(state)
                last_eval_step = at_step
            # state passed as-is: orbax's async save does the D2H
            # copy behind the next train steps (no device_get stall).
            t_c0 = time.monotonic() if trace else 0.0
            mgr.save(at_step, state, metrics=eval_metrics or None)
            if trace:
                tracer.record(trace["root"].trace_id, "ckpt", t_c0,
                              time.monotonic(),
                              parent_id=trace["root"].span_id,
                              attrs={"step": at_step})
            if recorder is not None:
                recorder.event("checkpoint", step=at_step)
            last_saved = at_step
            if watchdog is not None:
                watchdog.beat(at_step)

    def _state_event_at(at_step) -> bool:
        return _eval_due(at_step) or _ckpt_due(at_step)

    # Chunked (k>1) bookkeeping: the dispatched-but-not-yet-observed
    # chunk.  Its metrics fetch — the chunk's ONE host↔device sync — is
    # LAGGED one iteration: chunk n is flushed after chunk n+1 has been
    # dispatched, so the device always has work queued (run-ahead
    # preserved; through high-latency transports the dispatch gap would
    # otherwise idle the device once per chunk).  Boundaries that need
    # the post-chunk STATE (eval/checkpoint) flush synchronously before
    # the next dispatch instead — donation replaces the state.
    pending = None  # (end_step, metrics_device, epoch, chunk_trace)

    def _finish_chunk_trace(trace, at_step):
        """Close a sampled chunk's trace: synthesize the data-plane
        children (build/ring-wait/h2d durations accumulated by the
        pipeline THREADS during this chunk, placed at the root's start
        and tagged synthetic — durations are measured, placement is
        not), then end the root."""
        if not trace:
            return
        root = trace["root"]
        snap = data_stats.snapshot()
        for key, name in (("data_build_wait_ms", "build_wait"),
                          ("data_ring_wait_ms", "ring_wait"),
                          ("data_h2d_ms", "h2d")):
            dur_ms = snap.get(key, 0.0) - trace["snap"].get(key, 0.0)
            if dur_ms > 0:
                tracer.record(root.trace_id, name, root.t0,
                              root.t0 + dur_ms / 1000.0,
                              parent_id=root.span_id,
                              attrs={"synthetic": True})
        root.end(key=("train",), step=at_step)

    def _flush_chunk(with_state: bool):
        nonlocal pending, stop
        at_step, metrics_dev, at_epoch, trace = pending
        pending = None
        # The fetch cannot return before chunk `at_step` completed, so
        # it doubles as the completed-work signal — the timer/watchdog
        # beat is fed by finished device work, not by dispatch
        # (utils/timing.py).
        t_f0 = time.monotonic() if trace else 0.0
        metrics_host = jax.device_get(metrics_dev)
        if trace:
            tracer.record(trace["root"].trace_id, "flush", t_f0,
                          time.monotonic(),
                          parent_id=trace["root"].span_id)
        timer.tick(steps=k)
        _observe_capacity_slo(at_step - k)
        # Health observes EVERY fetched chunk (a mid-interval NaN must
        # reach the provenance counters even off the logging cadence).
        _observe_health(metrics_host)
        if "on_chunk_metrics" in hooks:
            hooks["on_chunk_metrics"](at_step, metrics_host)
        stop = _poll_stop(guard, at_step, sync_every) or stop
        if at_step % cfg.log_every_steps == 0 or at_step == total_steps:
            _process_log(at_step, metrics_host, at_epoch)
        if with_state:
            _run_state_events(at_step, trace=trace)
        _finish_chunk_trace(trace, at_step)

    # End-of-previous-chunk timestamp: the gap to the next body entry
    # is the chunk's data_wait span (blocked on the prefetch queue).
    # Only maintained while tracing is on — sample=0 reads no clocks.
    t_prev_end = None
    try:
      with PreemptionGuard() as guard:
        for epoch in itertools.count(start_epoch):
            if step >= total_steps or stop:
                break
            loader.set_epoch(epoch)
            # Host-side periodic re-validation rides BEFORE the H2D
            # prefetch (cheap numpy pass, no device sync); off unless
            # cfg.data.validate_every > 0.
            from ..utils.checks import periodic_validate

            host_batches = periodic_validate(iter(loader),
                                             cfg.data.validate_every)
            if k > 1:
                # Chunk assembly: stack k host batches along a new
                # leading axis BEFORE the H2D stage, so one transfer
                # ships a whole dispatch's worth (ring-buffer-aware —
                # see data/pipeline.py::chunk_batches).
                from ..data import chunk_batches

                host_batches = chunk_batches(host_batches, k,
                                             stats=data_stats)
            # mesh= (not sharding=): each host contributes its local
            # slice of the global batch — correct on multi-host pods.
            it = prefetch_to_device(
                host_batches, size=cfg.data.prefetch_batches, mesh=mesh,
                transfer_dtype=cfg.data.transfer_dtype,
                drop_keys=("index",),
                spec=batch_spec_override,
                stats=data_stats)
            for batch in it:
                if step >= total_steps or stop:
                    break
                if pending is not None and _state_event_at(pending[0]):
                    # Chunk n's eval/checkpoint must observe the state
                    # AT its boundary — flush before chunk n+1's
                    # donated dispatch replaces it.
                    _flush_chunk(with_state=True)
                    if stop:
                        break
                # Chunk trace: root spans the data wait + dispatch (+
                # flush/ckpt/eval recorded where they happen); None
                # unless this chunk is sampled.
                chunk_tr = None
                if tracer.enabled:
                    t_now = time.monotonic()
                    root = tracer.begin(
                        "chunk", mint_trace_id(),
                        t0=t_prev_end if t_prev_end is not None else t_now,
                        root=True,
                        attrs={"step_first": step + 1, "step_last": step + k,
                               "epoch": epoch})
                    if root is not None:
                        chunk_tr = {"root": root,
                                    "snap": data_stats.snapshot()}
                        if t_prev_end is not None:
                            tracer.record(root.trace_id, "data_wait",
                                          t_prev_end, t_now,
                                          parent_id=root.span_id)
                train_step = train_step_at(step)
                _maybe_record_capacity(step, train_step, state, batch)
                if plan is not None:
                    batch = plan.maybe_poison_batch(step + 1, batch)
                t_d0 = time.monotonic() if chunk_tr else 0.0
                if step == profile_at:
                    with profile_window(profile_dir):
                        state, metrics = train_step(state, batch)
                        jax.block_until_ready(metrics["total"])
                else:
                    state, metrics = train_step(state, batch)
                if chunk_tr:
                    # Host-side dispatch time (the device runs async;
                    # completed-work time shows up in the flush span).
                    tracer.record(chunk_tr["root"].trace_id, "dispatch",
                                  t_d0, time.monotonic(),
                                  parent_id=chunk_tr["root"].span_id)
                step += k
                if k > 1:
                    # Lagged flush: observe chunk n only after chunk
                    # n+1 is in flight, so the device never sits idle
                    # across the host's fetch + bookkeeping + dispatch
                    # gap (see _flush_chunk).
                    if pending is not None:
                        _flush_chunk(with_state=False)
                    pending = (step, metrics, epoch, chunk_tr)
                    if tracer.enabled:
                        t_prev_end = time.monotonic()
                    continue
                # ---- k == 1: the historical per-step path, unchanged.
                if plan is not None:
                    # Stall BEFORE the heartbeat: to the watchdog this
                    # step is still in flight, like a wedged dispatch.
                    plan.maybe_stall(step)
                timer.tick()
                _observe_capacity_slo(step - 1)
                if plan is not None:
                    plan.maybe_sigterm(step)
                stop = _poll_stop(guard, step, sync_every)
                if step % cfg.log_every_steps == 0 or step == total_steps:
                    # ONE batched device_get for the whole metric dict —
                    # not a blocking float(v) per scalar (each paid a
                    # full host↔device round trip on remote transports).
                    t_f0 = time.monotonic() if chunk_tr else 0.0
                    metrics_host = jax.device_get(metrics)
                    if chunk_tr:
                        tracer.record(chunk_tr["root"].trace_id, "flush",
                                      t_f0, time.monotonic(),
                                      parent_id=chunk_tr["root"].span_id)
                    _observe_health(metrics_host)
                    _process_log(step, metrics_host, epoch)
                _run_state_events(step, trace=chunk_tr)
                _finish_chunk_trace(chunk_tr, step)
                if tracer.enabled:
                    t_prev_end = time.monotonic()
            if step >= total_steps or stop:
                break
        if pending is not None:
            # The run's last chunk: nothing was dispatched after it, so
            # ``state`` is still its boundary state — flush with state
            # events before wind-down.
            _flush_chunk(with_state=True)
        if stop and recorder is not None:
            # Preemption (SIGTERM/SIGINT via the guard): the graceful
            # cousin of the replica SIGKILL — bundle the final window
            # before the wind-down checkpoint.
            recorder.event("preemption_stop", step=step)
            recorder.trigger("sigterm", "preemption guard stop")
        if watchdog is not None:
            # Training is over: the final eval/force-save/close below is
            # legitimate wind-down, not a wedged step.
            watchdog.stop()
        if step != last_saved:
            if (cfg.best_metric and eval_fn is not None
                    and last_eval_step != step):
                # Rank the final checkpoint with fresh measurements too.
                eval_metrics = eval_fn(state)
                last_eval_step = step
            mgr.save(step, state, metrics=eval_metrics or None, force=True)
    finally:
        if recorder is not None:
            import sys as _sys

            exc = _sys.exc_info()[1]
            if exc is not None:
                # A crashing fit (divergence RuntimeError, restore
                # failure, ...) bundles its last window on the way out
                # — the supervisor's rollback decision is then
                # post-mortemable from disk.
                recorder.trigger(
                    "train_crash",
                    f"{type(exc).__name__}: {exc}"[:200])
            recorder.stop()
        if telemetry is not None:
            telemetry.stop()
        if watchdog is not None:
            # Idempotent; also covers the exception paths, so the daemon
            # can never outlive fit() and 114 a healthy caller later.
            watchdog.stop()
        mgr.close()
        writer.close()
    last_metrics["final_step"] = step
    last_metrics.update({f"eval_{k}": v for k, v in eval_metrics.items()})
    return last_metrics


def _make_inline_eval(cfg: ExperimentConfig, model, mesh) -> Callable:
    """Build a lightweight in-training eval: max-Fβ/MAE over the
    held-out set (``data.val_root`` when set, else the train dataset —
    meaningful for overfit smoke tests, a real val set in production).
    Batches shard over the mesh's ``data`` axis, so eval reuses every
    chip the train step uses.  Feeds CheckpointManager's best-metric
    retention (cfg.best_metric)."""
    import dataclasses

    from ..eval import run_inference
    from ..eval.inference import make_forward
    from ..parallel.mesh import eval_batch_divisor, eval_batch_sharding

    data_cfg = cfg.data
    if cfg.data.val_root:
        data_cfg = dataclasses.replace(cfg.data, root=cfg.data.val_root)
    dataset = resolve_dataset(data_cfg)

    from ..parallel.sp import (make_sp_eval_forward, sp_eval_batch_size,
                               wants_sp_eval)

    # Which slice of the val set this process sweeps.  Host-disjoint
    # slices need batches that are NOT placed on the global mesh
    # (device_put onto non-addressable devices requires the same value
    # on every process), so the sharded sweep pairs with a HOST-LOCAL
    # eval mesh; the per-host metric states psum afterwards.
    shard = (0, 1)
    if wants_sp_eval(model, mesh):
        # Sequence-parallel forward (same helper as test.py's
        # evaluate()): image rows shard over ``seq`` with ring
        # attention, matching the train step's memory profile — a
        # full-attention eval would materialise the NxN scores the SP
        # run exists to avoid.  Batch shards over ``data`` only; the
        # seq axis may span hosts, so every host sweeps the full set
        # with identical batches (the global-placement contract).
        bs = sp_eval_batch_size(mesh, cfg.global_batch_size)
        make_eval_forward = make_sp_eval_forward(model, mesh,
                                                 cfg.mesh.sp_strategy)
    elif jax.process_count() > 1 and mesh.shape.get("model", 1) == 1:
        # Disjoint 1/num_hosts slice per host, on this host's own
        # chips only — total eval work is O(1) in host count and no
        # per-batch cross-host collectives.  Requires replicated
        # variables (model axis == 1): tensor-parallel params span
        # other hosts' devices and cannot be fetched host-locally, so
        # TP falls through to the global-mesh path below.
        import numpy as _np
        from jax.sharding import Mesh as _Mesh
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as _P

        from ..parallel.mesh import host_shard

        shard = host_shard()
        local = jax.local_devices()
        local_sharding = NamedSharding(
            _Mesh(_np.asarray(local), ("data",)), _P("data"))
        forward = make_forward(model)
        bs = max(1, cfg.global_batch_size // (len(local) *
                                              jax.process_count())
                 ) * len(local)

        def make_eval_forward(variables):
            # Off the global mesh first: arrays committed to a mesh
            # spanning other hosts' devices cannot join a host-local
            # computation (replicated arrays fetch locally for free).
            variables = jax.device_get(variables)
            return lambda b: forward(
                variables, jax.device_put(b, local_sharding))
    else:
        # jit once with the variables as an argument: re-invoking eval
        # does NOT retrace (same shapes), unlike a fresh closure per
        # call.  Batch dim over the flattened (data, seq) axes.
        forward = make_forward(model)
        div = eval_batch_divisor(mesh)
        bs = max(1, cfg.global_batch_size // div) * div

        def make_eval_forward(variables):
            return lambda b: forward(
                variables, jax.device_put(b, eval_batch_sharding(mesh)))

    def eval_fn(state) -> Dict[str, float]:
        from ..metrics.aggregator import results_from_state

        fwd = make_eval_forward(state.eval_variables())
        # Each host sweeps a DISJOINT 1/num_hosts slice of the val set
        # (not every host duplicating the full sweep), accumulating the
        # psum-able FBetaState inside jit at eval resolution; shard
        # states then sum across processes, so every host still
        # finalises identical metrics — best-k checkpoint ranking stays
        # consistent while total eval work is O(1) in host count.
        fstate = run_inference(
            fwd,
            dataset,
            batch_size=bs,
            use_depth=cfg.data.use_depth,
            compute_structure=False,
            device_metrics=True,
            shard=shard,
            return_state=True,
        )
        if shard[1] > 1:
            from jax.experimental import multihost_utils

            gathered = multihost_utils.process_allgather(fstate)
            fstate = jax.tree_util.tree_map(lambda x: x.sum(axis=0),
                                            gathered)
        return {k: v for k, v in results_from_state(fstate).items()
                if isinstance(v, float)}

    return eval_fn
