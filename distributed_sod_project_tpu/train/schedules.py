"""LR schedules (SURVEY.md §2 C9).

The reference idiom is poly decay ``lr·(1 − iter/max_iter)^0.9`` with
optional warmup; cosine and constant are provided for the zoo configs.
Schedules are pure ``step -> lr`` functions, so they trace into the
compiled train step (the LR is computed on device, not fed from host).
"""

from __future__ import annotations

import optax


def build_schedule(optim_cfg, total_steps: int) -> optax.Schedule:
    if total_steps <= 0:
        raise ValueError(f"total_steps must be positive, got {total_steps}")
    warmup = int(optim_cfg.warmup_steps)
    decay_steps = max(total_steps - warmup, 1)
    kind = optim_cfg.schedule
    if kind == "poly":
        main = optax.polynomial_schedule(
            init_value=optim_cfg.lr,
            end_value=0.0,
            power=optim_cfg.poly_power,
            transition_steps=decay_steps,
        )
    elif kind == "cosine":
        main = optax.cosine_decay_schedule(optim_cfg.lr, decay_steps)
    elif kind == "constant":
        main = optax.constant_schedule(optim_cfg.lr)
    else:
        raise ValueError(f"unknown schedule {kind!r}")
    if warmup > 0:
        ramp = optax.linear_schedule(0.0, optim_cfg.lr, warmup)
        return optax.join_schedules([ramp, main], [warmup])
    return main
