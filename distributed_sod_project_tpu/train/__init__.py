from .state import TrainState, create_train_state
from .schedules import build_schedule
from .optim import build_optimizer
from .step import make_eval_step

__all__ = [
    "TrainState",
    "create_train_state",
    "build_schedule",
    "build_optimizer",
    "make_eval_step",
]
