"""Train state — the single pytree the compiled step transforms.

Replaces the reference's mutable trio (model.state_dict(), optimizer
state, epoch counter; SURVEY.md §2 C11, §3.4) with one immutable pytree:
``train_step(state, batch) -> state`` with the input buffers donated, so
XLA updates parameters in place in HBM.

Static callables (``apply_fn``, the optax transform) live in closures,
NOT in the state, so the state is a pure array pytree — directly
serializable by orbax and shardable by pjit without pytree surgery.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
from flax import struct


@struct.dataclass
class TrainState:
    step: jnp.ndarray            # i32 scalar
    params: Any                  # f32 param pytree
    batch_stats: Any             # BatchNorm running stats (f32)
    opt_state: Any               # optax state
    ema_params: Any = None       # EMA of params (None = EMA disabled)
    # int8_ef error-feedback residual (parallel.grad_compression):
    # (n_data, n_grad_elems) f32, row r = replica r's accumulated
    # quantization error, sharded P('data') — per-replica state that
    # checkpoints with the rest of the pytree.  None when compression
    # is off (the overwhelmingly common case; pytree shape unchanged).
    comm_residual: Any = None

    def variables(self) -> Dict[str, Any]:
        return {"params": self.params, "batch_stats": self.batch_stats}

    def eval_variables(self) -> Dict[str, Any]:
        """Variables for evaluation: the EMA weights when tracked (the
        averaged model generalises better; reference-era repos get the
        same effect from picking the best epoch), else the raw params."""
        params = self.ema_params if self.ema_params is not None else self.params
        return {"params": params, "batch_stats": self.batch_stats}


def create_train_state(rng, model, tx, sample_batch,
                       pretrained: str = None,
                       ema: bool = False) -> TrainState:
    """Initialise params/batch_stats from one (host-side) sample batch
    and wrap them with the optimizer's initial state.  ``pretrained``
    merges a ported ImageNet backbone (.npz) over the fresh init.
    ``ema=True`` seeds the EMA tree as a copy of the initial params."""
    image = jnp.asarray(sample_batch["image"])
    depth = sample_batch.get("depth")
    if depth is not None:
        depth = jnp.asarray(depth)
    variables = model.init(rng, image, depth, train=False)
    if pretrained:
        from ..models.pretrained import load_pretrained

        variables = load_pretrained(variables, pretrained)
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        batch_stats=batch_stats,
        opt_state=tx.init(params),
        ema_params=jax.tree_util.tree_map(jnp.copy, params) if ema else None,
    )


def param_count(state: TrainState) -> int:
    return sum(x.size for x in jax.tree_util.tree_leaves(state.params))
