from .synthetic import SyntheticSOD
from .folder import FolderSOD, resolve_dataset
from .pipeline import HostDataLoader, prefetch_to_device

__all__ = [
    "SyntheticSOD",
    "FolderSOD",
    "resolve_dataset",
    "HostDataLoader",
    "prefetch_to_device",
]
