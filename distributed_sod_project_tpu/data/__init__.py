from .synthetic import SyntheticSOD
from .folder import FolderSOD, resolve_dataset
from .pipeline import HostDataLoader, chunk_batches, prefetch_to_device

__all__ = [
    "SyntheticSOD",
    "FolderSOD",
    "resolve_dataset",
    "HostDataLoader",
    "chunk_batches",
    "prefetch_to_device",
]
