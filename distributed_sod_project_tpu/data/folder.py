"""Directory-format SOD dataset loaders (SURVEY.md §2 C7).

Layouts supported (the idiomatic public-release layouts for these
datasets; the reference mount was unreadable, see SURVEY.md banner):

- DUTS:   ``<root>/DUTS-TR-Image/*.jpg`` + ``<root>/DUTS-TR-Mask/*.png``
          (or generically ``<root>/{Image,Mask}/``)
- RGB-D (NJU2K/NLPR): ``<root>/{RGB,depth,GT}/`` with matching stems.

Decoding + geometric transforms run host-side (XLA graphs stay static at
the configured size, SURVEY.md §7.3 hard part 5).  The heavy per-image
work (resize, normalize) is dispatched to the C++ runtime in
``native/`` when built, else falls back to PIL/numpy.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .synthetic import SyntheticSOD

_IMG_EXTS = (".jpg", ".jpeg", ".png", ".bmp")


def _index_dir(d: str) -> Dict[str, str]:
    out = {}
    for fn in sorted(os.listdir(d)):
        stem, ext = os.path.splitext(fn)
        if ext.lower() in _IMG_EXTS:
            out[stem] = os.path.join(d, fn)
    return out


def _find_subdir(root: str, candidates: Sequence[str]) -> Optional[str]:
    for c in candidates:
        p = os.path.join(root, c)
        if os.path.isdir(p):
            return p
    # Fuzzy: any subdir whose name ends with the candidate suffix.
    try:
        subdirs = sorted(
            d for d in os.listdir(root) if os.path.isdir(os.path.join(root, d))
        )
    except FileNotFoundError:
        return None
    for c in candidates:
        for d in subdirs:
            if d.lower().endswith(c.lower()):
                return os.path.join(root, d)
    return None


class FolderSOD:
    """Image/mask(/depth) triplets from a directory tree."""

    def __init__(
        self,
        root: str,
        image_size: Tuple[int, int] = (320, 320),
        use_depth: bool = False,
        normalize_mean: Tuple[float, float, float] = (0.485, 0.456, 0.406),
        normalize_std: Tuple[float, float, float] = (0.229, 0.224, 0.225),
        keep_original_size: bool = False,
    ):
        self.root = root
        self.image_size = image_size
        self.use_depth = use_depth
        self.mean = np.asarray(normalize_mean, np.float32)
        self.std = np.asarray(normalize_std, np.float32)
        self.keep_original_size = keep_original_size

        img_dir = _find_subdir(root, ["Image", "RGB", "Img", "images", "DUTS-TR-Image", "DUTS-TE-Image"])
        mask_dir = _find_subdir(root, ["Mask", "GT", "gt", "masks", "DUTS-TR-Mask", "DUTS-TE-Mask"])
        if img_dir is None or mask_dir is None:
            raise FileNotFoundError(
                f"could not locate Image/ and Mask/ (or RGB/ and GT/) under {root!r}"
            )
        imgs, masks = _index_dir(img_dir), _index_dir(mask_dir)
        stems = sorted(set(imgs) & set(masks))

        self.depth_paths: Optional[Dict[str, str]] = None
        if use_depth:
            depth_dir = _find_subdir(root, ["depth", "Depth", "depths"])
            if depth_dir is None:
                raise FileNotFoundError(f"use_depth=True but no depth/ under {root!r}")
            self.depth_paths = _index_dir(depth_dir)
            stems = sorted(set(stems) & set(self.depth_paths))

        if not stems:
            raise FileNotFoundError(f"no paired samples under {root!r}")
        self.stems: List[str] = stems
        self.img_paths = imgs
        self.mask_paths = masks

    def __len__(self) -> int:
        return len(self.stems)

    def _load(self, path: str, gray: bool) -> np.ndarray:
        from PIL import Image

        with Image.open(path) as im:
            im = im.convert("L" if gray else "RGB")
            if not self.keep_original_size:
                h, w = self.image_size
                im = im.resize((w, h), Image.BILINEAR)
            arr = np.asarray(im, dtype=np.float32) / 255.0
        if gray:
            arr = arr[..., None]
        return arr

    def __getitem__(self, index: int) -> Dict[str, np.ndarray]:
        stem = self.stems[index]
        img = self._load(self.img_paths[stem], gray=False)
        img = (img - self.mean) / self.std
        mask = self._load(self.mask_paths[stem], gray=True)
        mask = (mask > 0.5).astype(np.float32)
        out = {"image": img, "mask": mask, "index": np.int32(index)}
        if self.depth_paths is not None:
            out["depth"] = self._load(self.depth_paths[stem], gray=True)
        return out

    def load_batch(self, indices, hflip=None) -> Optional[Dict[str, np.ndarray]]:
        """Native C++ batch decode (data/native.py); None when the
        library is unbuilt or original sizes are kept (eval path)."""
        from . import native

        if self.keep_original_size or not native.available():
            return None
        stems = [self.stems[int(i)] for i in indices]
        kw = dict(size_hw=self.image_size, hflip=hflip)
        try:
            out = {
                "image": native.decode_batch(
                    [self.img_paths[s] for s in stems], gray=False,
                    mean=self.mean, std=self.std, **kw),
                "mask": (native.decode_batch(
                    [self.mask_paths[s] for s in stems], gray=True, **kw)
                    > 0.5).astype(np.float32),
                "index": np.asarray(indices, np.int32),
            }
            if self.depth_paths is not None:
                out["depth"] = native.decode_batch(
                    [self.depth_paths[s] for s in stems], gray=True, **kw)
        except RuntimeError:
            # Format the native decoder doesn't cover (BMP, CMYK JPEG…):
            # this batch — and, via the caller's latch, the rest of the
            # run — goes down the PIL path, which handles them all.
            return None
        return out


def resolve_dataset(cfg) -> object:
    """Build a dataset from a DataConfig; falls back to synthetic when the
    configured real-dataset root is absent (no network in this env).

    An existing ``root`` always wins — a user passing ``--data-root``
    to a config whose default dataset is synthetic means the files,
    not the fallback."""
    if cfg.root is None or not os.path.isdir(cfg.root):
        if cfg.dataset != "synthetic":
            from ..utils.logging import get_logger

            get_logger().warning(
                "dataset %r root %r not found — falling back to SYNTHETIC data; "
                "results will be meaningless for real benchmarks",
                cfg.dataset,
                cfg.root,
            )
        return SyntheticSOD(
            size=cfg.synthetic_size,
            image_size=cfg.image_size,
            use_depth=cfg.use_depth,
            normalize_mean=cfg.normalize_mean,
            normalize_std=cfg.normalize_std,
        )
    return FolderSOD(
        root=cfg.root,
        image_size=cfg.image_size,
        use_depth=cfg.use_depth,
        normalize_mean=cfg.normalize_mean,
        normalize_std=cfg.normalize_std,
    )
