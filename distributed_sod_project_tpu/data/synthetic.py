"""Deterministic synthetic SOD dataset.

The environment has no network and no real DUTS/NJU2K/NLPR data
(SURVEY.md §7.3 hard part 2), so CI and smoke training run on synthetic
image/mask pairs.  Samples are *learnable*, not noise: each image is a
textured background plus 1–3 bright elliptical "salient objects"; the
mask is the union of the ellipses.  A small CNN can overfit a batch of
these, which is what the integration tests assert (SURVEY.md §4).

Deterministic per (seed, index) so every host/worker regenerates
identical samples without coordination.
"""

from __future__ import annotations

from typing import Dict, Tuple

import numpy as np

# Decode-stage hot path (data plane): the coordinate grids are a pure
# function of the image size — rebuild per sample and they are ~10% of
# generation time.  Values identical to np.mgrid[...].astype(f32);
# the memoized read-only cache is shared with the rotation gather.
from .augment import _grid as _grids_cache


def _grids(h: int, w: int) -> Tuple[np.ndarray, np.ndarray]:
    return _grids_cache(h, w, np.float32)


class SyntheticSOD:
    def __init__(
        self,
        size: int = 256,
        image_size: Tuple[int, int] = (320, 320),
        use_depth: bool = False,
        seed: int = 0,
        normalize_mean: Tuple[float, float, float] = (0.485, 0.456, 0.406),
        normalize_std: Tuple[float, float, float] = (0.229, 0.224, 0.225),
    ):
        self.size = size
        self.image_size = image_size
        self.use_depth = use_depth
        self.seed = seed
        # Same mean/std normalization as FolderSOD, so the model input
        # distribution does not depend on the data source.
        self.mean = np.asarray(normalize_mean, np.float32)
        self.std = np.asarray(normalize_std, np.float32)

    def __len__(self) -> int:
        return self.size

    def __getitem__(self, index: int) -> Dict[str, np.ndarray]:
        h, w = self.image_size
        rng = np.random.default_rng(
            np.random.SeedSequence([self.seed, int(index)])
        )
        yy, xx = _grids(h, w)

        # Background: low-frequency texture from a coarse noise grid.
        # repeat() is the block-expand np.kron(coarse, ones((16,16,1)))
        # computes by multiplication — identical values, ~4x cheaper.
        coarse = rng.normal(0.35, 0.12, size=(h // 16 + 1, w // 16 + 1, 3))
        bg = (coarse.repeat(16, axis=0).repeat(16, axis=1)
              [:h, :w, :].astype(np.float32))

        mask = np.zeros((h, w), dtype=np.float32)
        img = bg.copy()
        for _ in range(int(rng.integers(1, 4))):
            cy, cx = rng.uniform(0.2, 0.8) * h, rng.uniform(0.2, 0.8) * w
            ry, rx = rng.uniform(0.08, 0.25) * h, rng.uniform(0.08, 0.25) * w
            theta = rng.uniform(0, np.pi)
            ct, st = np.cos(theta), np.sin(theta)
            u = (xx - cx) * ct + (yy - cy) * st
            v = -(xx - cx) * st + (yy - cy) * ct
            inside = (u / rx) ** 2 + (v / ry) ** 2 <= 1.0
            mask[inside] = 1.0
            color = rng.uniform(0.6, 1.0, size=3).astype(np.float32)
            img[inside] = 0.25 * img[inside] + 0.75 * color

        img = np.clip(img + rng.normal(0, 0.02, size=img.shape), 0.0, 1.0)
        img = (img - self.mean) / self.std
        out = {
            "image": img.astype(np.float32),
            "mask": mask[..., None],
            "index": np.int32(index),
        }
        if self.use_depth:
            # Depth: objects nearer (smaller depth) than background, with a
            # gradient — enough structure for the fusion path to exploit.
            depth = 0.8 - 0.5 * mask + 0.1 * (yy / h)
            depth += rng.normal(0, 0.02, size=depth.shape)
            out["depth"] = np.clip(depth, 0.0, 1.0).astype(np.float32)[..., None]
        return out
