"""ctypes binding for the native host data plane (native/dsod_host.cpp).

Optional fast path: when ``native/build/libdsod_host.so`` has been built
(``make -C native``), batched decode+resize+normalize(+hflip) runs in
C++ threads without the GIL; otherwise callers fall back to the PIL
path transparently (SURVEY.md §2.2 native-component row).
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import Optional, Sequence

import numpy as np

_lock = threading.Lock()
_lib = None
_tried = False


def _lib_path() -> str:
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(repo_root, "native", "build", "libdsod_host.so")


def load_library() -> Optional[ctypes.CDLL]:
    """The shared library, or None when unbuilt/unloadable."""
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        from ..utils import envvars

        path = envvars.read("DSOD_NATIVE_LIB")
        if path is None:  # '' stays '' — the empty-string-disables idiom
            path = _lib_path()
        if not os.path.exists(path):
            return None
        try:
            lib = ctypes.CDLL(path)
        except OSError:
            return None
        lib.dsod_decode_batch.restype = ctypes.c_int
        lib.dsod_decode_batch.argtypes = [
            ctypes.POINTER(ctypes.c_char_p), ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_float), ctypes.c_int,
        ]
        lib.dsod_version.restype = ctypes.c_int
        if hasattr(lib, "dsod_write_png_batch"):  # v2+ of the lib
            lib.dsod_write_png_batch.restype = ctypes.c_int
            lib.dsod_write_png_batch.argtypes = [
                ctypes.POINTER(ctypes.c_char_p),
                ctypes.POINTER(ctypes.c_void_p),
                ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
                ctypes.c_int, ctypes.c_int,
            ]
        _lib = lib
        return _lib


def available() -> bool:
    return load_library() is not None


def decode_batch(
    paths: Sequence[str],
    size_hw,
    *,
    gray: bool = False,
    hflip: Optional[Sequence[bool]] = None,
    mean=(0.0, 0.0, 0.0),
    std=(1.0, 1.0, 1.0),
    threads: int = 0,
) -> np.ndarray:
    """Decode ``paths`` → [N,H,W,C] float32, resized/normalised/flipped.

    Raises RuntimeError naming the first file that failed to decode.
    """
    lib = load_library()
    if lib is None:
        raise RuntimeError("native library not built (make -C native)")
    n = len(paths)
    h, w = int(size_hw[0]), int(size_hw[1])
    c = 1 if gray else 3
    out = np.empty((n, h, w, c), np.float32)
    c_paths = (ctypes.c_char_p * n)(*[p.encode() for p in paths])
    mean_a = (ctypes.c_float * c)(*([float(m) for m in mean[:c]] if not gray
                                    else [float(mean[0])]))
    std_a = (ctypes.c_float * c)(*([float(s) for s in std[:c]] if not gray
                                   else [float(std[0])]))
    flip_buf = None
    if hflip is not None:
        flip_buf = bytes(bytearray(1 if f else 0 for f in hflip))
    rc = lib.dsod_decode_batch(
        c_paths, n, h, w, int(gray), flip_buf, mean_a, std_a,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), int(threads))
    if rc:
        raise RuntimeError(f"native decode failed for {paths[rc - 1]!r}")
    return out


def png_writer_available() -> bool:
    lib = load_library()
    return lib is not None and hasattr(lib, "dsod_write_png_batch")


def write_png_batch(items, threads: int = 0) -> None:
    """Write grayscale PNGs in C++ threads (no GIL).

    ``items``: sequence of (path, uint8 [H,W] array); arrays may have
    different shapes (per-image original resolutions on the eval path).
    Raises RuntimeError naming the first failed write.
    """
    lib = load_library()
    if lib is None or not hasattr(lib, "dsod_write_png_batch"):
        raise RuntimeError("native PNG writer unavailable "
                           "(make -C native, lib v2+)")
    n = len(items)
    if n == 0:
        return
    arrays = []
    for _, a in items:
        a = np.ascontiguousarray(a)
        if a.dtype != np.uint8 or a.ndim != 2:
            raise ValueError(f"want uint8 [H,W], got {a.dtype} {a.shape}")
        arrays.append(a)
    c_paths = (ctypes.c_char_p * n)(*[p.encode() for p, _ in items])
    c_data = (ctypes.c_void_p * n)(*[a.ctypes.data for a in arrays])
    c_w = (ctypes.c_int * n)(*[a.shape[1] for a in arrays])
    c_h = (ctypes.c_int * n)(*[a.shape[0] for a in arrays])
    rc = lib.dsod_write_png_batch(c_paths, c_data, c_w, c_h, n,
                                  int(threads))
    if rc:
        raise RuntimeError(f"native PNG write failed for "
                           f"{items[rc - 1][0]!r}")
