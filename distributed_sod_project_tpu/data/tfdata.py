"""tf.data input backend (SURVEY.md §2.2, [B:5] "feeds TPU hosts via
tf.data").

An alternative to the default ``HostDataLoader``(+C++ decoder) with the
same contract — per-host shard of every global batch, epoch-seeded
deterministic shuffling, numpy dict batches — built from tf.data's
parallel map/prefetch machinery.  Select with
``--set data.backend=tfdata``.

Sharding follows the DistributedSampler semantics the reference used
(SURVEY.md §2 C4): one global permutation per epoch (same seed on every
host), each host taking its contiguous slice of every global batch — so
shards are disjoint and covering, and batch composition is identical to
the host-loader backend.

TensorFlow is imported lazily and pinned to CPU: it is a host-side data
library here; the accelerators belong to JAX.
"""

from __future__ import annotations

from typing import Dict, Iterator, Optional

import numpy as np


def _tf():
    import tensorflow as tf

    try:  # never let tf grab the accelerators
        tf.config.set_visible_devices([], "GPU")
        tf.config.set_visible_devices([], "TPU")
    except Exception:  # noqa: BLE001 — best-effort on exotic builds
        pass
    return tf


class TFDataLoader:
    """HostDataLoader-compatible loader over a file-backed FolderSOD."""

    def __init__(
        self,
        dataset,
        global_batch_size: int,
        shard_id: int = 0,
        num_shards: int = 1,
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = True,
        hflip: bool = False,
        rotate_degrees: float = 0.0,
        color_jitter: float = 0.0,
        num_workers: int = 4,
        skip_budget: int = 0,
    ):
        self.rotate_degrees = float(rotate_degrees)
        self.color_jitter = float(color_jitter)
        # Corrupt-sample degradation (resilience/dataguard.py): with a
        # budget, decode errors are dropped inside the TF graph
        # (ignore_errors) and the resulting epoch-end batch shortfall
        # is charged against the budget; without one, the first decode
        # error propagates (fail fast, the historical behavior).
        self.skip_budget = int(skip_budget)
        self.skipped = 0
        if global_batch_size % num_shards != 0:
            raise ValueError(
                f"global_batch_size={global_batch_size} not divisible by "
                f"num_shards={num_shards}")
        if not hasattr(dataset, "stems"):
            raise ValueError(
                "tfdata backend needs a file-backed dataset (FolderSOD); "
                "use the default host backend for synthetic data")
        self.dataset = dataset
        self.global_batch_size = global_batch_size
        self.local_batch_size = global_batch_size // num_shards
        self.shard_id = shard_id
        self.num_shards = num_shards
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.hflip = hflip
        self.num_workers = num_workers
        self._epoch = 0
        self._skip = 0

    def set_epoch(self, epoch: int) -> None:
        self._epoch = epoch

    def skip_steps(self, n: int) -> None:
        """One-shot mid-epoch resume offset (see HostDataLoader)."""
        self._skip = int(n)

    @property
    def steps_per_epoch(self) -> int:
        n = len(self.dataset)
        if self.drop_last:
            return n // self.global_batch_size
        return -(-n // self.global_batch_size)

    def _epoch_order(self, epoch: int) -> np.ndarray:
        n = len(self.dataset)
        if self.shuffle:
            rng = np.random.default_rng(np.random.SeedSequence([self.seed, epoch]))
            order = rng.permutation(n)
        else:
            order = np.arange(n)
        if not self.drop_last and n % self.global_batch_size:
            pad = self.global_batch_size - n % self.global_batch_size
            order = np.concatenate([order, order[:pad]])
        return order

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        tf = _tf()
        ds_obj = self.dataset
        h, w = ds_obj.image_size
        mean = tf.constant(ds_obj.mean, tf.float32)
        std = tf.constant(ds_obj.std, tf.float32)
        use_depth = ds_obj.depth_paths is not None
        epoch = self._epoch
        aug_seed = hash((self.seed, epoch)) & 0x7FFFFFFF

        # This host's slice of every global batch, in global epoch order.
        order = self._epoch_order(epoch)
        steps = self.steps_per_epoch
        start, self._skip = self._skip, 0
        my = np.concatenate([
            order[s * self.global_batch_size
                  + self.shard_id * self.local_batch_size:
                  s * self.global_batch_size
                  + (self.shard_id + 1) * self.local_batch_size]
            for s in range(start, steps)]) if steps > start else np.zeros(
                (0,), np.int64)

        stems = [ds_obj.stems[i] for i in my]
        img_paths = [ds_obj.img_paths[s] for s in stems]
        mask_paths = [ds_obj.mask_paths[s] for s in stems]
        tensors = {
            "index": my.astype(np.int32),
            "img_path": img_paths,
            "mask_path": mask_paths,
        }
        if use_depth:
            tensors["depth_path"] = [ds_obj.depth_paths[s] for s in stems]
        if self.hflip:
            # The SHARED per-index draws (data/augment.py), precomputed
            # host-side: TF's stateless RNG disagrees with the numpy
            # draws per sample, which would silently make the training
            # stream depend on the backend choice; a graph-constant
            # column keeps the map pure (no py callbacks on the decode
            # path, dataset stays serializable).
            from .augment import hflip_draw

            tensors["flip"] = np.array(
                [hflip_draw(aug_seed, int(i)) for i in my], np.bool_)
        if self.color_jitter:
            # Same precomputed-constant pattern as the flip column:
            # the (brightness, saturation, contrast) factors come from
            # the shared data/augment.py draws, the arithmetic below
            # mirrors apply_color_jitter in pure TF ops.
            from .augment import jitter_draw

            tensors["jitter"] = np.array(
                [jitter_draw(aug_seed, int(i), self.color_jitter)
                 for i in my], np.float32)

        def decode(rec):
            img = tf.io.decode_image(tf.io.read_file(rec["img_path"]),
                                     channels=3, expand_animations=False)
            img = tf.image.resize(tf.cast(img, tf.float32), (h, w),
                                  antialias=True) / 255.0
            mask = tf.io.decode_image(tf.io.read_file(rec["mask_path"]),
                                      channels=1, expand_animations=False)
            mask = tf.image.resize(tf.cast(mask, tf.float32), (h, w),
                                   antialias=True) / 255.0
            mask = tf.cast(mask > 0.5, tf.float32)
            if self.color_jitter:
                # Mirrors augment.apply_color_jitter: brightness ->
                # saturation -> contrast on the still-unnormalized
                # [0, 1] image (jitter here, THEN normalize once — no
                # denorm/renorm round trip).  Runs before hflip
                # (commutes) and before the rotation py_function (must
                # not see zero-fill corners in the contrast mean).
                from .augment import _LUMA

                b, s_, c = (rec["jitter"][0], rec["jitter"][1],
                            rec["jitter"][2])
                raw = img * b
                gray = tf.reduce_sum(
                    raw * tf.constant(_LUMA), axis=-1, keepdims=True)
                raw = gray + (raw - gray) * s_
                gmean = tf.reduce_mean(gray)
                raw = gmean + (raw - gmean) * c
                img = tf.clip_by_value(raw, 0.0, 1.0)
            img = (img - mean) / std
            out = {"image": img, "mask": mask, "index": rec["index"]}
            if use_depth:
                d = tf.io.decode_image(tf.io.read_file(rec["depth_path"]),
                                       channels=1, expand_animations=False)
                out["depth"] = tf.image.resize(
                    tf.cast(d, tf.float32), (h, w), antialias=True) / 255.0
            if self.hflip:
                flip = rec["flip"]
                for k in ("image", "mask", "depth"):
                    if k in out:
                        out[k] = tf.cond(
                            flip, lambda t=out[k]: tf.reverse(t, axis=[1]),
                            lambda t=out[k]: t)
            # Rotation happens OUTSIDE the graph, on the assembled
            # numpy batch, through the shared vectorized augment
            # (data/augment.py rotate_batch) — same per-index draws as
            # the host/grain backends, one gather per batch instead of
            # a GIL-serialised py_function per sample.
            return out

        ds = (tf.data.Dataset.from_tensor_slices(tensors)
              .map(decode, num_parallel_calls=max(1, self.num_workers)))
        if self.skip_budget > 0:
            # Drop undecodable samples inside the graph instead of
            # killing the epoch; the shortfall check below bounds how
            # many may vanish before we fail anyway.
            ds = ds.apply(tf.data.experimental.ignore_errors())
        ds = ds.batch(self.local_batch_size, drop_remainder=True).prefetch(2)
        got = 0
        for batch in ds.as_numpy_iterator():
            batch.pop("img_path", None)
            batch.pop("mask_path", None)
            batch.pop("depth_path", None)
            got += 1
            if self.rotate_degrees:
                from .augment import rotate_batch, rotate_draw_batch

                batch = rotate_batch(
                    batch, rotate_draw_batch(aug_seed, batch["index"],
                                             self.rotate_degrees))
            yield batch
        if self.skip_budget > 0:
            # ignore_errors is silent; charge the observable effect —
            # whole batches missing at epoch end — against the budget
            # so unbounded skipping can't shrink the dataset quietly.
            # Only a FULLY-DRAINED epoch can be charged: on an early
            # break (total_steps reached, preemption stop) the shortfall
            # is indistinguishable from batches the consumer never asked
            # for, so that partial epoch goes uncounted rather than
            # false-positively exhausting the budget.
            lost = (steps - start - got) * self.local_batch_size
            if lost > 0:
                self.skipped += lost
            if self.skipped > self.skip_budget:
                from ..resilience.dataguard import SkipBudgetExhausted

                raise SkipBudgetExhausted(
                    f"tfdata epoch {epoch} lost ≥{lost} samples to decode "
                    f"errors; total skipped {self.skipped} exceeds "
                    f"skip_budget={self.skip_budget}")


def make_loader(dataset, data_cfg, **kw):
    """Backend dispatch: 'host' (default), 'tfdata', or 'grain'.

    ``skip_budget`` is consumed here: the host/grain backends fetch
    sample-by-sample through the (possibly GuardedDataset-wrapped)
    dataset, which enforces the budget itself; only the tf.data
    backend — which decodes inside the TF graph, bypassing
    ``dataset[i]`` — needs the budget to drive its own
    ignore_errors + shortfall degradation (see TFDataLoader).

    The host-pipeline tuning knobs (``lookahead``, ``ring_buffers``,
    ``decode_procs`` — see DataConfig) are injected from ``data_cfg``
    here so callers only pass the cross-backend parameters; ``stats``
    (a utils/observability.PipelineStats) is forwarded to the host
    backend, which is the one with instrumented blocking points.
    """
    backend = getattr(data_cfg, "backend", "host")
    skip_budget = int(kw.pop("skip_budget", 0))
    stats = kw.pop("stats", None)
    # Host-only knobs: consumed here so tfdata/grain callers may pass
    # them uniformly (their execution layers have their own buffering).
    host_kw = {}
    for knob in ("lookahead", "ring_buffers", "decode_procs",
                 "cache_decoded", "cache_budget_mb"):
        if knob in kw:
            host_kw[knob] = kw.pop(knob)
        elif hasattr(data_cfg, knob):
            host_kw[knob] = getattr(data_cfg, knob)
    if backend == "tfdata":
        return TFDataLoader(dataset, skip_budget=skip_budget, **kw)
    if backend == "grain":
        from .grain_pipeline import GrainLoader

        return GrainLoader(dataset, **kw)
    if backend == "host":
        from .pipeline import HostDataLoader

        return HostDataLoader(dataset, stats=stats, **kw, **host_kw)
    raise ValueError(f"unknown data backend {backend!r}")
