"""Host-side input pipeline with per-host sharding.

Replaces the reference's ``DataLoader`` + ``DistributedSampler`` pair
(SURVEY.md §2 C4/C7) with the TPU idiom: every host materialises only
its 1/num_shards slice of each global batch, and epoch-seeded shuffling
plays the role of ``sampler.set_epoch`` — identical permutations on all
hosts without any cross-host coordination.

The data plane is multi-stage (docs/PERFORMANCE.md "Host data plane"):

  decode workers → batch buffers (ring) → vectorized augment
      → staging (ordered futures) → H2D thread (prefetch_to_device)

- ``num_workers`` build threads assemble whole batches in parallel
  (``lookahead`` batches in flight), writing samples straight into
  preallocated output buffers — no per-step ``np.stack``.
- augmentation is the whole-batch vectorized path in data/augment.py
  (same per-(seed, epoch, idx) draws as the scalar reference).
- ``ring_buffers`` > 0 recycles the batch buffers instead of
  allocating per step.  CONTRACT: a yielded batch's arrays are valid
  until ``_RING_KEEP`` further batches have been yielded; consumers
  that hold batches longer (tests collecting an epoch) must copy or
  run with the ring off (the default).
- ``decode_procs`` > 0 decodes samples in a process pool writing into
  shared-memory ring slots — sidesteps the GIL for the PIL decode path
  when the C++ runtime in ``native/`` is unbuilt.
- every blocking point feeds ``PipelineStats``
  (utils/observability.py), so "input-bound" is a number
  (``data_starved_ms``), not a guess.
"""

from __future__ import annotations

import collections
import concurrent.futures as cf
import queue
import threading
import time
from typing import Dict, Iterator, Optional

import numpy as np

# A yielded batch stays valid for this many further yields in ring mode
# (the consumer typically holds the current batch while requesting the
# next: keep = 2 covers "current + one downstream stage").
_RING_KEEP = 2


class BatchRing:
    """Preallocated ring of reusable batch buffers (dicts of arrays).

    ``acquire`` blocks until a slot is free (natural producer
    backpressure, the wait is recorded as ``data_ring_wait_ms``);
    ``release`` returns a slot to the pool.  With ``shared=True`` the
    arrays live in ``multiprocessing.shared_memory`` segments so
    process-pool decode workers can write rows directly — zero-copy
    transport instead of pickling every sample back.
    """

    def __init__(self, nslots: int, spec: Dict[str, tuple],
                 shared: bool = False, stats=None):
        self.nslots = int(nslots)
        self.spec = dict(spec)
        self._stats = stats
        self._free: "queue.Queue" = queue.Queue()
        self._shm = []
        self._shm_spec: Dict[int, Dict[str, tuple]] = {}
        self.slots = []
        for _ in range(self.nslots):
            slot: Dict[str, np.ndarray] = {}
            sspec: Dict[str, tuple] = {}
            for k, (shape, dtype) in self.spec.items():
                if shared:
                    from multiprocessing import shared_memory

                    nbytes = int(np.prod(shape)) * np.dtype(dtype).itemsize
                    seg = shared_memory.SharedMemory(
                        create=True, size=max(nbytes, 1))
                    self._shm.append(seg)
                    slot[k] = np.ndarray(shape, dtype, buffer=seg.buf)
                    sspec[k] = (seg.name, shape, np.dtype(dtype).str)
                else:
                    slot[k] = np.empty(shape, dtype)
            self.slots.append(slot)
            self._shm_spec[id(slot)] = sspec
            self._free.put(slot)

    def acquire(self) -> Dict[str, np.ndarray]:
        try:
            return self._free.get_nowait()
        except queue.Empty:
            pass
        t0 = time.perf_counter()
        slot = self._free.get()
        if self._stats is not None:
            self._stats.add("data_ring_wait_ms",
                            (time.perf_counter() - t0) * 1000.0)
        return slot

    def release(self, slot: Dict[str, np.ndarray]) -> None:
        self._free.put(slot)

    def shm_spec(self, slot) -> Dict[str, tuple]:
        """Picklable {key: (shm_name, shape, dtype)} for proc workers."""
        return self._shm_spec[id(slot)]

    def close(self) -> None:
        for seg in self._shm:
            try:
                seg.close()
                seg.unlink()
            except Exception:  # noqa: BLE001 — already unlinked / torn down
                pass
        self._shm = []


# --- process-pool decode workers (shared-memory transport) -----------------
# Module-level so they pickle under both fork and spawn; the dataset
# rides the initializer once per worker, not once per task.

_PROC_DS = None
_PROC_SHM: Dict[str, "object"] = {}


def _proc_init(dataset) -> None:
    global _PROC_DS
    _PROC_DS = dataset


def _proc_decode_into(task) -> int:
    """Decode one sample into row ``row`` of the shm-backed slot
    described by ``spec``; returns the dataset index (ack)."""
    idx, row, spec = task
    from multiprocessing import shared_memory

    sample = _PROC_DS[int(idx)]
    for k, (name, shape, dtype) in spec.items():
        seg = _PROC_SHM.get(name)
        if seg is None:
            seg = _PROC_SHM[name] = shared_memory.SharedMemory(name=name)
        arr = np.ndarray(shape, np.dtype(dtype), buffer=seg.buf)
        arr[row] = sample[k]
    return int(idx)


class HostDataLoader:
    """Epoch-based, shard-aware, deterministic batch iterator.

    Yields dicts of numpy arrays with leading dim = per-host batch size
    (= global_batch_size // num_shards).  Batch content is a pure
    function of (seed, epoch, step) — identical for any ``num_workers``,
    ``lookahead``, ``ring_buffers`` or ``decode_procs`` setting
    (asserted in tests/test_data_plane.py).
    """

    def __init__(
        self,
        dataset,
        global_batch_size: int,
        shard_id: int = 0,
        num_shards: int = 1,
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = True,
        hflip: bool = False,
        rotate_degrees: float = 0.0,
        color_jitter: float = 0.0,
        num_workers: int = 0,
        lookahead: int = 2,
        ring_buffers: int = 0,
        decode_procs: int = 0,
        cache_decoded: int = -1,
        cache_budget_mb: int = 1024,
        stats=None,
    ):
        if global_batch_size % num_shards != 0:
            raise ValueError(
                f"global_batch_size={global_batch_size} not divisible by "
                f"num_shards={num_shards}"
            )
        self.dataset = dataset
        self.global_batch_size = global_batch_size
        self.local_batch_size = global_batch_size // num_shards
        self.shard_id = shard_id
        self.num_shards = num_shards
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.hflip = hflip
        self.rotate_degrees = float(rotate_degrees)
        self.color_jitter = float(color_jitter)
        self.num_workers = num_workers
        # lookahead = batches in flight; below num_workers it would
        # silently idle configured build threads, so it saturates them.
        self.lookahead = max(int(lookahead), 1, int(num_workers))
        # decode_procs needs shm slots to write into → implies a ring.
        self.ring_buffers = int(ring_buffers)
        if decode_procs > 0 and self.ring_buffers == 0:
            self.ring_buffers = self.lookahead + _RING_KEEP + 2
        if self.ring_buffers:
            # Slots must cover in-flight builds + the validity window +
            # one being handed over, or builders deadlock on acquire.
            self.ring_buffers = max(self.ring_buffers,
                                    self.lookahead + _RING_KEEP + 1)
        self.decode_procs = int(decode_procs)
        self.cache_decoded = int(cache_decoded)
        self.cache_budget_mb = int(cache_budget_mb)
        self.stats = stats
        self._epoch = 0
        self._skip = 0
        self._ring: Optional[BatchRing] = None
        self._proc_pool = None
        self._cache: Optional[Dict[int, dict]] = None
        self._cache_max = 0

    def set_epoch(self, epoch: int) -> None:
        self._epoch = epoch

    def skip_steps(self, n: int) -> None:
        """Start the NEXT iteration ``n`` batches into the epoch (exact
        mid-epoch resume: order is a pure function of (seed, epoch), so
        skipping is index arithmetic, no data is touched).  One-shot —
        consumed by the next ``__iter__``."""
        self._skip = int(n)

    @property
    def steps_per_epoch(self) -> int:
        n = len(self.dataset)
        if self.drop_last:
            return n // self.global_batch_size
        return -(-n // self.global_batch_size)

    def _epoch_order(self, epoch: int) -> np.ndarray:
        n = len(self.dataset)
        if self.shuffle:
            rng = np.random.default_rng(np.random.SeedSequence([self.seed, epoch]))
            order = rng.permutation(n)
        else:
            order = np.arange(n)
        if not self.drop_last and n % self.global_batch_size:
            pad = self.global_batch_size - n % self.global_batch_size
            order = np.concatenate([order, order[:pad]])
        return order

    # ------------------------------------------------------------------
    # batch assembly
    # ------------------------------------------------------------------

    def _batch_spec(self) -> Dict[str, tuple]:
        """{key: (batch_shape, dtype)} probed from sample 0 — the shapes
        are static per dataset (XLA contract), so one probe serves the
        whole run."""
        sample = self.dataset[0]
        return {
            k: ((self.local_batch_size,) + np.asarray(v).shape,
                np.asarray(v).dtype)
            for k, v in sample.items()
        }

    def _decode_into(self, buf: Dict[str, np.ndarray], idxs) -> None:
        """Fill buffer rows with RAW (unaugmented) samples — the decode
        stage.  Corrupt-sample handling stays in the dataset wrapper
        (resilience/dataguard.py), which this calls through."""
        if self._proc_pool is not None and self._ring is not None:
            spec = self._ring.shm_spec(buf)
            if spec:
                try:
                    tasks = [(int(i), j, spec) for j, i in enumerate(idxs)]
                    # The timeout converts a wedged worker (fork-
                    # inherited lock, dead child) into the in-thread
                    # fallback instead of an eternal hang.
                    list(self._proc_pool.map(_proc_decode_into, tasks,
                                             timeout=300))
                    return
                except Exception as e:  # noqa: BLE001 — broken pool/
                    # pickle: permanent for this run; fall back to
                    # in-process.  Data-integrity raises are NOT infra
                    # failures and must keep propagating.
                    from ..resilience.dataguard import SkipBudgetExhausted

                    if isinstance(e, SkipBudgetExhausted):
                        raise
                    self._teardown_procs()
                    from ..utils.logging import get_logger

                    get_logger().warning(
                        "process-pool decode failed — falling back to "
                        "in-thread decode for the rest of the run")
        cache = self._cache
        for j, i in enumerate(idxs):
            ii = int(i)
            sample = cache.get(ii) if cache is not None else None
            if sample is None:
                sample = self.dataset[ii]
                if cache is not None and len(cache) < self._cache_max:
                    cache[ii] = sample
            for k in buf:
                buf[k][j] = sample[k]

    def _setup_cache(self) -> None:
        """Raw-decoded-sample memoization (the tf.data ``cache()``
        analogue): when the dataset fits the RAM budget, every epoch
        after the first costs a row copy instead of a decode.  Safe by
        construction — augmentation always runs AFTER the copy into the
        batch buffer, so cached samples are never mutated and the
        per-epoch draw streams stay exact."""
        if self._cache is not None or self.cache_decoded == 0:
            return
        n = len(self.dataset)
        want = n if self.cache_decoded < 0 else min(n, self.cache_decoded)
        if self.cache_decoded < 0:
            probe = self.dataset[0]
            nbytes = sum(np.asarray(v).nbytes for v in probe.values())
            if nbytes * n > self.cache_budget_mb * (1 << 20):
                want = 0  # auto mode: dataset exceeds the budget
        self._cache_max = want
        self._cache = {} if want > 0 else None
        if want <= 0:
            self.cache_decoded = 0  # resolved: don't re-probe each epoch

    def _build(self, step: int, order: np.ndarray, aug_seed: int
               ) -> Dict[str, np.ndarray]:
        """One full batch: acquire buffers → decode → vectorized
        augment.  Runs on a build worker; pure function of step."""
        from .augment import augment_batch

        lo = (step * self.global_batch_size
              + self.shard_id * self.local_batch_size)
        idxs = order[lo:lo + self.local_batch_size]
        if self._ring is not None:
            buf = self._ring.acquire()
        else:
            buf = {k: np.empty(shape, dtype)
                   for k, (shape, dtype) in self._spec.items()}
        self._decode_into(buf, idxs)
        return augment_batch(
            buf, idxs, aug_seed, hflip=self.hflip,
            rotate_degrees=self.rotate_degrees,
            color_jitter=self.color_jitter,
            norm_mean=getattr(self.dataset, "mean", None),
            norm_std=getattr(self.dataset, "std", None),
            reuse_buffers=self._ring is not None)

    def _build_native(self, idxs, native_batch, aug_seed: int):
        """C++ data plane: whole-batch decode (+hflip) without the GIL,
        then the same vectorized jitter/rotation.  Returns None when the
        library bows out (unbuilt / unsupported format)."""
        from .augment import augment_batch, hflip_draw_batch

        flags = (hflip_draw_batch(aug_seed, idxs) if self.hflip
                 else [False] * len(idxs))
        batch = native_batch(idxs, hflip=list(map(bool, flags)))
        if batch is None:
            return None
        return augment_batch(
            batch, idxs, aug_seed, hflip=False, skip_hflip=True,
            rotate_degrees=self.rotate_degrees,
            color_jitter=self.color_jitter,
            norm_mean=getattr(self.dataset, "mean", None),
            norm_std=getattr(self.dataset, "std", None))

    def _setup_procs(self) -> None:
        if self.decode_procs <= 0 or self._proc_pool is not None:
            return
        from ..resilience.dataguard import GuardedDataset

        if isinstance(self.dataset, GuardedDataset):
            # Each worker process would get its own COPY of the guard,
            # so corrupt-sample counts would never reach the parent's
            # skip-budget accounting (data_skipped metric, budget
            # exhaustion) — the PR-1 bounded-corruption invariant.
            # Decode in-thread instead, loudly.
            self.decode_procs = 0
            from ..utils.logging import get_logger

            get_logger().warning(
                "data.decode_procs is incompatible with the corrupt-"
                "sample skip budget (GuardedDataset state is per-"
                "process) — decoding in-thread instead")
            return
        import multiprocessing as mp

        try:
            # spawn, not fork: the pool starts lazily from a worker
            # thread of an already-multithreaded (jax-initialized)
            # process, where fork can inherit held locks and deadlock
            # children.  Workers import only numpy-level modules, so
            # spawn startup is cheap and paid once per run.
            from ..utils import envvars

            ctx = mp.get_context(envvars.read("DSOD_DECODE_MP"))
            self._proc_pool = cf.ProcessPoolExecutor(
                max_workers=self.decode_procs, mp_context=ctx,
                initializer=_proc_init, initargs=(self.dataset,))
        except Exception:  # noqa: BLE001 — unpicklable dataset etc.
            self._teardown_procs()
            from ..utils.logging import get_logger

            get_logger().warning(
                "could not start %d decode processes — decoding "
                "in-thread instead", self.decode_procs)

    def _teardown_procs(self) -> None:
        pool, self._proc_pool = self._proc_pool, None
        if pool is not None:
            pool.shutdown(wait=False)

    def close(self) -> None:
        """Release ring shm + decode processes (idempotent)."""
        self._teardown_procs()
        if self._ring is not None:
            self._ring.close()
            self._ring = None

    def __del__(self):  # best-effort: shm segments must not leak
        try:
            self.close()
        except Exception:  # noqa: BLE001
            pass

    # ------------------------------------------------------------------

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        epoch = self._epoch
        order = self._epoch_order(epoch)
        steps = self.steps_per_epoch
        start, self._skip = self._skip, 0
        aug_seed = hash((self.seed, epoch)) & 0x7FFFFFFF

        # C++ data plane: whole-batch decode without the GIL.  Probed on
        # the first step; None is sticky for the run (lib unbuilt /
        # format unsupported) and the Python pipeline takes over.
        native_batch = getattr(self.dataset, "load_batch", None)
        if native_batch is not None:
            while start < steps:
                lo = (start * self.global_batch_size
                      + self.shard_id * self.local_batch_size)
                idxs = order[lo:lo + self.local_batch_size]
                batch = self._build_native(idxs, native_batch, aug_seed)
                if batch is None:
                    break  # Python pipeline takes over from `start`
                if self.stats is not None:
                    self.stats.add("data_batches", 1.0)
                start += 1
                yield batch
            if start >= steps:
                return  # native served the whole epoch

        if self.ring_buffers and self._ring is None:
            self._ring = BatchRing(self.ring_buffers, self._batch_spec(),
                                   shared=self.decode_procs > 0,
                                   stats=self.stats)
        if self._ring is None:
            self._spec = self._batch_spec()
        self._setup_procs()
        self._setup_cache()

        yielded: "collections.deque" = collections.deque()

        def emit(batch):
            if self._ring is not None:
                yielded.append(batch)
                if len(yielded) > _RING_KEEP:
                    self._ring.release(yielded.popleft())
            if self.stats is not None:
                self.stats.add("data_batches", 1.0)
            return batch

        if self.num_workers <= 0:
            try:
                for step in range(start, steps):
                    yield emit(self._build(step, order, aug_seed))
            finally:
                while yielded:
                    self._ring.release(yielded.popleft())
            return

        pool = cf.ThreadPoolExecutor(max_workers=self.num_workers)
        inflight: "collections.deque" = collections.deque()
        try:
            horizon = min(self.lookahead, self.num_workers)
            nxt = start
            while nxt < min(start + horizon, steps):
                inflight.append(pool.submit(self._build, nxt, order,
                                            aug_seed))
                nxt += 1
            while inflight:
                fut = inflight.popleft()
                t0 = time.perf_counter()
                batch = fut.result()
                if self.stats is not None:
                    self.stats.add("data_build_wait_ms",
                                   (time.perf_counter() - t0) * 1000.0)
                if nxt < steps:
                    inflight.append(pool.submit(self._build, nxt,
                                                order, aug_seed))
                    nxt += 1
                # Register BEFORE yielding: if the consumer closes the
                # generator at this yield, the slot is still tracked
                # and the finally below reclaims it.
                emit(batch)
                yield batch
        finally:
            # Early close must not strand ring slots: release the
            # validity window first (unblocks builders waiting in
            # acquire), then reclaim the in-flight builds' slots.
            if self._ring is not None:
                while yielded:
                    self._ring.release(yielded.popleft())
            for fut in inflight:
                if not fut.cancel() and self._ring is not None:
                    try:
                        self._ring.release(fut.result(timeout=60))
                    except Exception:  # noqa: BLE001 — builder died; its
                        pass  # slot is lost but the ring stays usable
            pool.shutdown(wait=False)


def chunk_batches(iterator, steps_per_dispatch: int, stats=None):
    """Stack ``steps_per_dispatch`` consecutive host batches along a new
    leading axis — the chunk-assembly stage feeding the scanned train
    step (``train.steps_per_dispatch``; docs/PERFORMANCE.md).

    Sits BETWEEN the loader and ``prefetch_to_device`` so one H2D
    transfer ships a whole chunk.  Ring-buffer-aware: each incoming
    batch is copied into the chunk buffer the moment it is yielded, so
    the loader's ``_RING_KEEP``-yield validity window is honored for
    any k (the assembler never holds a loader batch across a yield).

    Chunk buffers rotate as a pair, mirroring ``prefetch_to_device``'s
    cast buffers and inheriting the same safety argument: a yielded
    chunk is consumed by the H2D thread, which blocks until the (async)
    transfer lands before pulling the next chunk, so buffer i is only
    rewritten after chunk i's copy completed (on the CPU backend the
    prefetch worker snapshots host arrays instead — ``device_put`` may
    alias — so reuse is safe there too).

    A trailing partial chunk (epoch length not divisible by k — fit()
    validates this never happens) is dropped, counted into the
    ``data_partial_chunks_dropped`` stat rather than silently shipped
    with stale rows.
    """
    k = int(steps_per_dispatch)
    if k < 1:
        raise ValueError(f"steps_per_dispatch must be >= 1, got {k}")
    if k == 1:
        yield from iterator
        return
    bufs: list = [None, None]
    flip = 0
    filled = 0
    t_asm = 0.0
    for batch in iterator:
        if filled == 0:
            t_asm = 0.0
            buf = bufs[flip]
            stale = (buf is None or set(buf) != set(batch) or any(
                buf[key].shape[1:] != np.asarray(v).shape
                or buf[key].dtype != np.asarray(v).dtype
                for key, v in batch.items()))
            if stale:
                bufs[flip] = {
                    key: np.empty((k,) + np.asarray(v).shape,
                                  np.asarray(v).dtype)
                    for key, v in batch.items()}
        t0 = time.perf_counter()
        for key, v in batch.items():
            bufs[flip][key][filled] = v
        t_asm += time.perf_counter() - t0
        filled += 1
        if filled == k:
            if stats is not None:
                stats.add("data_chunk_assemble_ms", t_asm * 1000.0)
                stats.add("data_chunks", 1.0)
            out = bufs[flip]
            flip ^= 1
            filled = 0
            yield out
    if filled and stats is not None:
        stats.add("data_partial_chunks_dropped", 1.0)


def prefetch_to_device(iterator, size: int = 2, sharding=None, mesh=None,
                       transfer_dtype=None, drop_keys=(), spec=None,
                       stats=None):
    """Wrap a host batch iterator with a background H2D thread that
    stages batches onto device ahead of consumption (the final stage of
    the multi-stage pipeline; the TPU analogue of the reference's
    pinned-memory ``non_blocking`` H2D copies in SURVEY.md §3.1).

    Pass ``mesh`` for a batch-sharded global array built from each
    host's local slice (``make_array_from_process_local_data`` — the
    multi-host-correct path); ``sharding`` is the single-host
    device_put path.

    ``transfer_dtype`` (e.g. ``"bfloat16"``) casts image/depth on the
    host before the copy — halves H2D bytes when the input pipeline is
    transfer-bound; the model computes in its own ``compute_dtype``
    regardless.  Masks stay f32 (binary values are exact either way,
    but the loss reduces in f32).  The cast reuses a rotating pair of
    preallocated buffers per key (cast-into-buffer, not a second
    malloc+copy per step) — safe because the H2D thread blocks until
    each (async) transfer lands before touching the sibling buffer
    again; on the CPU backend, where ``device_put`` may alias host
    memory outright, the reuse is disabled and batches are snapshotted
    instead.

    ``stats`` (utils/observability.PipelineStats) records
    ``data_starved_ms`` (consumer blocked on an empty queue — the
    "input-bound" number), ``data_h2d_ms`` (device_put time),
    ``data_prefetch_full_ms`` (producer blocked on a full queue: the
    healthy, compute-bound direction) and queue-depth samples.

    Producer-thread exceptions propagate to the consumer; closing the
    generator early unblocks and stops the producer.
    """
    import jax

    cast = None
    if transfer_dtype and str(transfer_dtype) != "float32":
        import ml_dtypes  # ships with jax

        cast = np.dtype(getattr(ml_dtypes, str(transfer_dtype), None)
                        or transfer_dtype)

    # CPU jax may make device arrays that alias the source numpy buffer
    # (zero-copy device_put): never recycle cast buffers there, and
    # snapshot every host array before the put so upstream buffer
    # recycling (BatchRing) can never mutate an in-flight device batch.
    # Real accelerators copy host->HBM, so neither cost exists there.
    on_cpu = jax.default_backend() == "cpu"
    reuse_cast = cast is not None and not on_cpu
    cast_bufs: Dict[tuple, list] = {}

    def cast_into(k, arr, flip):
        if not reuse_cast:
            return np.asarray(arr).astype(cast)
        pair = cast_bufs.get(k)
        if pair is None or pair[0].shape != arr.shape:
            pair = cast_bufs[k] = [np.empty(arr.shape, cast),
                                   np.empty(arr.shape, cast)]
        buf = pair[flip]
        np.copyto(buf, arr, casting="unsafe")
        return buf

    def maybe_cast(batch, flip):
        if cast is None and not drop_keys:
            return batch
        out = dict(batch)
        for k in drop_keys:  # loader metadata the step never reads
            out.pop(k, None)
        if cast is not None:
            for k in ("image", "depth"):
                if k in out:
                    out[k] = cast_into(k, out[k], flip)
        return out

    q: "queue.Queue" = queue.Queue(maxsize=size)
    stop = threading.Event()
    _END = object()

    def worker():
        flip = 0
        try:
            for batch in iterator:
                batch = maybe_cast(batch, flip)
                flip ^= 1
                if on_cpu:
                    # cast outputs are already fresh on cpu (reuse_cast
                    # off) — don't copy those twice.
                    fresh = {"image", "depth"} if cast is not None else ()
                    batch = {k: (np.array(v)
                                 if isinstance(v, np.ndarray)
                                 and k not in fresh else v)
                             for k, v in batch.items()}
                if stop.is_set():
                    return
                t0 = time.perf_counter()
                if mesh is not None:
                    from ..parallel.mesh import global_batch_array

                    batch = global_batch_array(batch, mesh, spec=spec)
                elif sharding is not None:
                    batch = jax.device_put(batch, sharding)
                else:
                    batch = jax.device_put(batch)
                if not on_cpu:
                    # H2D transfers are ASYNC: the host buffers (ring
                    # slots, rotating cast buffers) must stay immutable
                    # until the copy lands.  Waiting here, on the H2D
                    # thread, bounds in-flight reuse without stalling
                    # the consumer — the device batch had to finish
                    # transferring before a step could read it anyway.
                    jax.block_until_ready(batch)
                if stats is not None:
                    stats.add("data_h2d_ms",
                              (time.perf_counter() - t0) * 1000.0)
                t0 = time.perf_counter()
                while not stop.is_set():
                    try:
                        q.put(batch, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if stats is not None:
                    stats.add("data_prefetch_full_ms",
                              (time.perf_counter() - t0) * 1000.0)
                if stop.is_set():
                    return
            q.put(_END)
        except BaseException as e:  # noqa: BLE001 — relayed to the consumer
            while not stop.is_set():
                try:
                    q.put(e, timeout=0.1)
                    break
                except queue.Full:
                    continue

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    try:
        while True:
            if stats is not None:
                stats.observe_depth(q.qsize(), size)
            t0 = time.perf_counter()
            item = q.get()
            if stats is not None:
                stats.add("data_starved_ms",
                          (time.perf_counter() - t0) * 1000.0)
            if item is _END:
                break
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        stop.set()
        # Drain so a producer blocked on a full queue can observe `stop`,
        # then join: a daemon thread torn down mid device transfer at
        # interpreter exit aborts the process with a C++ exception.
        try:
            while True:
                q.get_nowait()
        except queue.Empty:
            pass
        t.join(timeout=5.0)
