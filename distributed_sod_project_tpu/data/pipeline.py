"""Host-side input pipeline with per-host sharding.

Replaces the reference's ``DataLoader`` + ``DistributedSampler`` pair
(SURVEY.md §2 C4/C7) with the TPU idiom: every host materialises only
its 1/num_shards slice of each global batch, and epoch-seeded shuffling
plays the role of ``sampler.set_epoch`` — identical permutations on all
hosts without any cross-host coordination.

Decode/augment runs in a thread pool (the C++ runtime in ``native/``
provides the heavy kernels when built); ``prefetch_to_device`` overlaps
host work with device steps.
"""

from __future__ import annotations

import concurrent.futures as cf
import queue
import threading
from typing import Dict, Iterator

import numpy as np


class HostDataLoader:
    """Epoch-based, shard-aware, deterministic batch iterator.

    Yields dicts of numpy arrays with leading dim = per-host batch size
    (= global_batch_size // num_shards).
    """

    def __init__(
        self,
        dataset,
        global_batch_size: int,
        shard_id: int = 0,
        num_shards: int = 1,
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = True,
        hflip: bool = False,
        rotate_degrees: float = 0.0,
        color_jitter: float = 0.0,
        num_workers: int = 0,
    ):
        if global_batch_size % num_shards != 0:
            raise ValueError(
                f"global_batch_size={global_batch_size} not divisible by "
                f"num_shards={num_shards}"
            )
        self.dataset = dataset
        self.global_batch_size = global_batch_size
        self.local_batch_size = global_batch_size // num_shards
        self.shard_id = shard_id
        self.num_shards = num_shards
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.hflip = hflip
        self.rotate_degrees = float(rotate_degrees)
        self.color_jitter = float(color_jitter)
        self.num_workers = num_workers
        self._epoch = 0
        self._skip = 0

    def set_epoch(self, epoch: int) -> None:
        self._epoch = epoch

    def skip_steps(self, n: int) -> None:
        """Start the NEXT iteration ``n`` batches into the epoch (exact
        mid-epoch resume: order is a pure function of (seed, epoch), so
        skipping is index arithmetic, no data is touched).  One-shot —
        consumed by the next ``__iter__``."""
        self._skip = int(n)

    @property
    def steps_per_epoch(self) -> int:
        n = len(self.dataset)
        if self.drop_last:
            return n // self.global_batch_size
        return -(-n // self.global_batch_size)

    def _epoch_order(self, epoch: int) -> np.ndarray:
        n = len(self.dataset)
        if self.shuffle:
            rng = np.random.default_rng(np.random.SeedSequence([self.seed, epoch]))
            order = rng.permutation(n)
        else:
            order = np.arange(n)
        if not self.drop_last and n % self.global_batch_size:
            pad = self.global_batch_size - n % self.global_batch_size
            order = np.concatenate([order, order[:pad]])
        return order

    @staticmethod
    def _hflip_draw(aug_seed: int, idx: int) -> bool:
        from .augment import hflip_draw

        return hflip_draw(aug_seed, idx)

    def _fetch(self, idx: int, aug_seed: int) -> Dict[str, np.ndarray]:
        from .augment import augment_sample

        sample = dict(self.dataset[int(idx)])
        return augment_sample(sample, int(idx), aug_seed,
                              hflip=self.hflip,
                              rotate_degrees=self.rotate_degrees,
                              color_jitter=self.color_jitter,
                              norm_mean=getattr(self.dataset, "mean", None),
                              norm_std=getattr(self.dataset, "std", None))

    def _rotate_batch(self, batch, idxs, aug_seed: int):
        """Rotation for the native-decode path (which handled decode +
        hflip in C++): same per-index draws as the PIL path."""
        from .augment import apply_rotate, rotate_draw

        per_image = [
            apply_rotate({k: batch[k][j] for k in ("image", "mask", "depth")
                          if k in batch},
                         rotate_draw(aug_seed, int(i), self.rotate_degrees))
            for j, i in enumerate(idxs)]
        out = dict(batch)
        for k in per_image[0]:
            out[k] = np.stack([s[k] for s in per_image])
        return out

    def _jitter_batch(self, batch, idxs, aug_seed: int):
        """Color jitter for the native-decode path — same per-index
        draws as the PIL path.  Jitter commutes with hflip (pixelwise
        given per-image stats), so applying it after the C++ flip is
        identical to the augment_sample order; it must still run
        BEFORE rotation (zero-fill corners shift the contrast mean)."""
        from .augment import apply_color_jitter, jitter_draw

        mean = getattr(self.dataset, "mean", None)
        std = getattr(self.dataset, "std", None)
        imgs = [apply_color_jitter(
                    {"image": batch["image"][j]},
                    jitter_draw(aug_seed, int(i), self.color_jitter),
                    mean, std)["image"]
                for j, i in enumerate(idxs)]
        out = dict(batch)
        out["image"] = np.stack(imgs)
        return out

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        epoch = self._epoch
        order = self._epoch_order(epoch)
        steps = self.steps_per_epoch
        start, self._skip = self._skip, 0
        aug_seed = hash((self.seed, epoch)) & 0x7FFFFFFF

        pool = (
            cf.ThreadPoolExecutor(max_workers=self.num_workers)
            if self.num_workers > 0
            else None
        )
        native_batch = getattr(self.dataset, "load_batch", None)
        try:
            for step in range(start, steps):
                lo = step * self.global_batch_size + self.shard_id * self.local_batch_size
                idxs = order[lo : lo + self.local_batch_size]
                if native_batch is not None:
                    # C++ data plane: whole-batch decode without the GIL,
                    # same per-index hflip draws as the PIL path.
                    flags = [self.hflip and self._hflip_draw(aug_seed, i)
                             for i in idxs]
                    batch = native_batch(idxs, hflip=flags)
                    if batch is not None:
                        if self.color_jitter:
                            batch = self._jitter_batch(batch, idxs, aug_seed)
                        if self.rotate_degrees:
                            batch = self._rotate_batch(batch, idxs, aug_seed)
                        yield batch
                        continue
                    # Latch off: None is sticky (lib unbuilt / format
                    # unsupported) — don't redo the probe every step.
                    native_batch = None
                if pool is not None:
                    samples = list(pool.map(lambda i: self._fetch(i, aug_seed), idxs))
                else:
                    samples = [self._fetch(i, aug_seed) for i in idxs]
                batch = {
                    k: np.stack([s[k] for s in samples]) for k in samples[0]
                }
                yield batch
        finally:
            if pool is not None:
                pool.shutdown(wait=False)


def prefetch_to_device(iterator, size: int = 2, sharding=None, mesh=None,
                       transfer_dtype=None, drop_keys=(), spec=None):
    """Wrap a host batch iterator with a background thread that stages
    batches onto device ahead of consumption (H2D overlap, the TPU
    analogue of the reference's pinned-memory ``non_blocking`` H2D copies
    in SURVEY.md §3.1).

    Pass ``mesh`` for a batch-sharded global array built from each
    host's local slice (``make_array_from_process_local_data`` — the
    multi-host-correct path); ``sharding`` is the single-host
    device_put path.

    ``transfer_dtype`` (e.g. ``"bfloat16"``) casts image/depth on the
    host before the copy — halves H2D bytes when the input pipeline is
    transfer-bound; the model computes in its own ``compute_dtype``
    regardless.  Masks stay f32 (binary values are exact either way,
    but the loss reduces in f32).

    Producer-thread exceptions propagate to the consumer; closing the
    generator early unblocks and stops the producer.
    """
    import jax

    cast = None
    if transfer_dtype and str(transfer_dtype) != "float32":
        import ml_dtypes  # ships with jax

        cast = np.dtype(getattr(ml_dtypes, str(transfer_dtype), None)
                        or transfer_dtype)

    def maybe_cast(batch):
        if cast is None and not drop_keys:
            return batch
        out = dict(batch)
        for k in drop_keys:  # loader metadata the step never reads
            out.pop(k, None)
        if cast is not None:
            for k in ("image", "depth"):
                if k in out:
                    out[k] = np.asarray(out[k]).astype(cast)
        return out

    q: "queue.Queue" = queue.Queue(maxsize=size)
    stop = threading.Event()
    _END = object()

    def worker():
        try:
            for batch in iterator:
                batch = maybe_cast(batch)
                if stop.is_set():
                    return
                if mesh is not None:
                    from ..parallel.mesh import global_batch_array

                    batch = global_batch_array(batch, mesh, spec=spec)
                elif sharding is not None:
                    batch = jax.device_put(batch, sharding)
                else:
                    batch = jax.device_put(batch)
                while not stop.is_set():
                    try:
                        q.put(batch, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if stop.is_set():
                    return
            q.put(_END)
        except BaseException as e:  # noqa: BLE001 — relayed to the consumer
            while not stop.is_set():
                try:
                    q.put(e, timeout=0.1)
                    break
                except queue.Full:
                    continue

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    try:
        while True:
            item = q.get()
            if item is _END:
                break
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        stop.set()
        # Drain so a producer blocked on a full queue can observe `stop`,
        # then join: a daemon thread torn down mid device transfer at
        # interpreter exit aborts the process with a C++ exception.
        try:
            while True:
                q.get_nowait()
        except queue.Empty:
            pass
        t.join(timeout=5.0)
