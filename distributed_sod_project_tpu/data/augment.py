"""Shared train-time augmentations (SURVEY.md §2 C7).

The reference-era SOD training recipe augments with horizontal flips
plus small random rotations (MINet's joint transforms rotate up to
±10°).  Both are implemented here as pure functions of
``(aug_seed, sample index)`` so every backend draws identically and
mid-epoch resume replays the exact stream (data/pipeline.py contract).

Rotation runs host-side on the decoded float arrays: bilinear for
image/depth, nearest for the binary mask, constant fill — matching the
torchvision ``rotate(expand=False)`` convention.
"""

from __future__ import annotations

from typing import Dict

import numpy as np


def hflip_draw(aug_seed: int, idx: int) -> bool:
    rng = np.random.default_rng(np.random.SeedSequence([aug_seed, int(idx)]))
    return bool(rng.random() < 0.5)


def rotate_draw(aug_seed: int, idx: int, degrees: float) -> float:
    """Deterministic angle in [-degrees, +degrees] for this sample.
    A distinct stream from hflip (offset key) so the two draws stay
    independent."""
    rng = np.random.default_rng(
        np.random.SeedSequence([aug_seed ^ 0x5EED, int(idx)]))
    return float((rng.random() * 2.0 - 1.0) * degrees)


def apply_hflip(sample: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    out = dict(sample)
    for k in ("image", "mask", "depth"):
        if k in out:
            out[k] = np.ascontiguousarray(out[k][:, ::-1])
    return out


def apply_rotate(sample: Dict[str, np.ndarray],
                 angle_deg: float) -> Dict[str, np.ndarray]:
    """Rotate image/depth bilinearly and the mask nearest by
    ``angle_deg`` about the center, same spatial shape (expand=False)."""
    if abs(angle_deg) < 1e-6:
        return sample
    from scipy import ndimage

    out = dict(sample)
    for k, order in (("image", 1), ("depth", 1), ("mask", 0)):
        if k in out:
            arr = out[k]
            rot = ndimage.rotate(arr, angle_deg, axes=(1, 0),
                                 reshape=False, order=order,
                                 mode="constant", cval=0.0)
            out[k] = np.ascontiguousarray(rot.astype(arr.dtype))
    return out


def augment_sample(sample: Dict[str, np.ndarray], idx: int, aug_seed: int,
                   *, hflip: bool, rotate_degrees: float
                   ) -> Dict[str, np.ndarray]:
    """The full deterministic train-time augmentation for one sample."""
    if hflip and hflip_draw(aug_seed, idx):
        sample = apply_hflip(sample)
    if rotate_degrees:
        sample = apply_rotate(sample, rotate_draw(aug_seed, idx,
                                                  rotate_degrees))
    return sample
