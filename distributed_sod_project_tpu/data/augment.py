"""Shared train-time augmentations (SURVEY.md §2 C7).

The reference-era SOD training recipe augments with horizontal flips
plus small random rotations (MINet's joint transforms rotate up to
±10°).  Both are implemented here as pure functions of
``(aug_seed, sample index)`` so every backend draws identically and
mid-epoch resume replays the exact stream (data/pipeline.py contract).

Rotation runs host-side on the decoded float arrays: bilinear for
image/depth, nearest for the binary mask, constant fill — matching the
torchvision ``rotate(expand=False)`` convention.

Two implementations of the same math live here:

- the SCALAR path (``augment_sample`` and the ``apply_*`` helpers) —
  one sample at a time, rotation via ``scipy.ndimage``.  This is the
  reference semantics, kept for per-sample callers and as the ground
  truth the batch path is tested against.
- the BATCH path (``augment_batch`` and the ``*_batch`` helpers) —
  whole-batch numpy: hflip via a boolean row mask, jitter via broadcast
  factor columns, rotation via a per-image affine coordinate map and a
  flat bilinear/nearest gather.  Same per-``(aug_seed, idx)`` draw
  streams (the draws themselves are shared), bitwise-identical outputs
  for hflip/jitter and ≤1e-5 from scipy for rotation
  (tests/test_data_plane.py).  This is what all three loader backends
  run in production — the scalar path does per-sample Python work
  (N ``scipy.ndimage.rotate`` calls per batch) that made the host
  pipeline the throughput wall (docs/PERFORMANCE.md "Host data plane").
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np


def hflip_draw(aug_seed: int, idx: int) -> bool:
    rng = np.random.default_rng(np.random.SeedSequence([aug_seed, int(idx)]))
    return bool(rng.random() < 0.5)


def rotate_draw(aug_seed: int, idx: int, degrees: float) -> float:
    """Deterministic angle in [-degrees, +degrees] for this sample.
    A distinct stream from hflip (offset key) so the two draws stay
    independent."""
    rng = np.random.default_rng(
        np.random.SeedSequence([aug_seed ^ 0x5EED, int(idx)]))
    return float((rng.random() * 2.0 - 1.0) * degrees)


def jitter_draw(aug_seed: int, idx: int, strength: float):
    """Deterministic (brightness, saturation, contrast) factors, each
    in [1-strength, 1+strength] — a distinct stream from hflip/rotate
    (offset key) so all draws stay independent."""
    rng = np.random.default_rng(
        np.random.SeedSequence([aug_seed ^ 0xC0108, int(idx)]))
    f = 1.0 + (rng.random(3) * 2.0 - 1.0) * strength
    return float(f[0]), float(f[1]), float(f[2])


_LUMA = np.asarray([0.299, 0.587, 0.114], np.float32)


def apply_color_jitter(sample: Dict[str, np.ndarray], factors,
                       mean, std) -> Dict[str, np.ndarray]:
    """Brightness → saturation → contrast on the IMAGE only (masks and
    depth untouched), computed in the unnormalized [0, 1] space (the
    sample arrives mean/std-normalized) and clipped back to the data
    range — the torchvision ColorJitter semantics with a fixed
    application order so every backend agrees bit-for-bit.

    Applied BEFORE rotation: contrast normalizes around the gray mean,
    and rotation's zero-fill corners would shift that statistic.
    """
    b, s, c = factors
    mean = np.asarray(mean if mean is not None else 0.0, np.float32)
    std = np.asarray(std if std is not None else 1.0, np.float32)
    img = sample["image"].astype(np.float32)
    raw = img * std + mean
    raw = raw * b
    gray = (raw @ _LUMA)[..., None]
    raw = gray + (raw - gray) * s
    gmean = np.float32(gray.mean())
    raw = gmean + (raw - gmean) * c
    raw = np.clip(raw, 0.0, 1.0)
    out = dict(sample)
    out["image"] = ((raw - mean) / std).astype(sample["image"].dtype)
    return out


def apply_hflip(sample: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    out = dict(sample)
    for k in ("image", "mask", "depth"):
        if k in out:
            out[k] = np.ascontiguousarray(out[k][:, ::-1])
    return out


def apply_rotate(sample: Dict[str, np.ndarray],
                 angle_deg: float) -> Dict[str, np.ndarray]:
    """Rotate image/depth bilinearly and the mask nearest by
    ``angle_deg`` about the center, same spatial shape (expand=False)."""
    if abs(angle_deg) < 1e-6:
        return sample
    from scipy import ndimage

    out = dict(sample)
    for k, order in (("image", 1), ("depth", 1), ("mask", 0)):
        if k in out:
            arr = out[k]
            rot = ndimage.rotate(arr, angle_deg, axes=(1, 0),
                                 reshape=False, order=order,
                                 mode="constant", cval=0.0)
            out[k] = np.ascontiguousarray(rot.astype(arr.dtype))
    return out


def augment_sample(sample: Dict[str, np.ndarray], idx: int, aug_seed: int,
                   *, hflip: bool, rotate_degrees: float,
                   color_jitter: float = 0.0, norm_mean=None, norm_std=None
                   ) -> Dict[str, np.ndarray]:
    """The full deterministic train-time augmentation for one sample:
    color jitter (photometric, image only) → hflip → rotation.

    Scalar REFERENCE path — production batches go through
    :func:`augment_batch` (same draws, vectorized application)."""
    if color_jitter:
        sample = apply_color_jitter(
            sample, jitter_draw(aug_seed, idx, color_jitter),
            norm_mean, norm_std)
    if hflip and hflip_draw(aug_seed, idx):
        sample = apply_hflip(sample)
    if rotate_degrees:
        sample = apply_rotate(sample, rotate_draw(aug_seed, idx,
                                                  rotate_degrees))
    return sample


# ---------------------------------------------------------------------------
# Vectorized whole-batch path.
#
# The draws stay per-index scalar calls (one tiny SeedSequence each —
# bit-for-bit the streams above; vectorizing THEM would change the
# bits), while the pixel work is batch-level numpy.
# ---------------------------------------------------------------------------


def hflip_draw_batch(aug_seed: int, idxs: Sequence[int]) -> np.ndarray:
    """``[hflip_draw(aug_seed, i) for i in idxs]`` as a bool column."""
    return np.asarray([hflip_draw(aug_seed, int(i)) for i in idxs],
                      np.bool_)


def rotate_draw_batch(aug_seed: int, idxs: Sequence[int],
                      degrees: float) -> np.ndarray:
    """Per-index rotation angles, same stream as :func:`rotate_draw`."""
    return np.asarray([rotate_draw(aug_seed, int(i), degrees)
                       for i in idxs], np.float64)


def jitter_draw_batch(aug_seed: int, idxs: Sequence[int],
                      strength: float) -> np.ndarray:
    """[len(idxs), 3] (brightness, saturation, contrast) factor matrix,
    same streams as :func:`jitter_draw`."""
    return np.asarray([jitter_draw(aug_seed, int(i), strength)
                       for i in idxs], np.float64)


def apply_color_jitter_batch(images: np.ndarray, factors: np.ndarray,
                             mean, std,
                             out: Optional[np.ndarray] = None) -> np.ndarray:
    """Whole-batch :func:`apply_color_jitter`: [B,H,W,3] images,
    [B,3] factors, broadcast factor columns instead of per-sample
    Python.  Bitwise-identical to the scalar path: every elementwise op
    runs in float32 on the same values in the same order, and the
    per-image gray mean (the one true reduction) is computed per sample
    exactly as the scalar path computes it.

    ``out`` may alias ``images`` (ring-slot reuse): the input pixels are
    fully consumed into temporaries before the final write.
    """
    mean = np.asarray(mean if mean is not None else 0.0, np.float32)
    std = np.asarray(std if std is not None else 1.0, np.float32)
    bsc = factors.astype(np.float32)
    if out is None:
        out = np.empty_like(images)
    # Per-image chunks: one image's working set is cache-resident where
    # a whole-batch pass streams ~40 MB through DRAM per op (this box
    # measured 4x slower batch-wide).  Same ops, same order, same
    # values per sample as apply_color_jitter → bitwise equal.
    for j in range(images.shape[0]):
        b, s, c = bsc[j, 0], bsc[j, 1], bsc[j, 2]
        img = (images[j] if images.dtype == np.float32
               else images[j].astype(np.float32))
        raw = img * std + mean
        raw *= b
        gray = (raw @ _LUMA)[..., None]
        # tmp = gray + (raw - gray) * s, elementwise in float32 — the
        # in-place forms round identically to the scalar path's
        # expression.
        raw -= gray
        raw *= s
        raw += gray
        gmean = np.float32(gray.mean())
        raw -= gmean
        raw *= c
        raw += gmean
        np.clip(raw, 0.0, 1.0, out=raw)
        raw -= mean
        raw /= std
        np.copyto(out[j], raw, casting="unsafe")
    return out


def apply_hflip_batch(batch: Dict[str, np.ndarray],
                      flips: np.ndarray) -> None:
    """In-place width-axis flip of the flagged rows of every spatial
    key ([B,H,W,C] layout; the scalar path flips sample axis 1 = W,
    which is batch axis 2)."""
    if not flips.any():
        return
    for k in ("image", "mask", "depth"):
        if k in batch:
            batch[k][flips] = batch[k][flips][:, :, ::-1]


_GRIDS: Dict[tuple, tuple] = {}


def _grid(h: int, w: int, dtype=np.float64):
    """Memoized read-only ``np.mgrid[0:h, 0:w]`` pair — shared by the
    rotation gather (float64 coords) and SyntheticSOD's decode
    (float32); rebuilding these per call is measurable on the hot
    path.  Read-only: the cache hands the same arrays to every
    caller."""
    key = (h, w, np.dtype(dtype).str)
    g = _GRIDS.get(key)
    if g is None:
        yy, xx = np.mgrid[0:h, 0:w].astype(dtype)
        yy.setflags(write=False)
        xx.setflags(write=False)
        g = _GRIDS[key] = (yy, xx)
    return g


def _rotate_gather(plane: np.ndarray, sy, sx, valid, invalid_any: bool,
                   order: int, out: np.ndarray) -> None:
    """Sample one [H,W,C] plane at source coords (sy, sx) into ``out``.

    order=1 bilinear / order=0 nearest, constant-0 outside [0, n-1]
    on either axis — scipy.ndimage's ``mode='constant'`` semantics
    (no edge/cval interpolation; verified against scipy in tests).
    ``sy``/``sx`` arrive pre-clipped into the valid range; ``valid``
    marks which outputs keep their sampled value.
    """
    h, w, c = plane.shape
    flat = plane.reshape(h * w, c)
    if order == 0:
        iy = np.floor(sy + 0.5).astype(np.int32)
        iy *= w
        iy += np.floor(sx + 0.5).astype(np.int32)
        # 1-channel planes (the mask): a flat 1D take is ~2x a row take.
        if c == 1:
            out[...] = plane.reshape(-1).take(iy.ravel()).reshape(h, w, 1)
        else:
            out[...] = np.take(flat, iy.ravel(), axis=0).reshape(h, w, c)
    else:
        y0 = np.minimum(np.floor(sy), h - 2)
        x0 = np.minimum(np.floor(sx), w - 2)
        wy = (sy - y0).astype(np.float32)[..., None]
        wx = (sx - x0).astype(np.float32)[..., None]
        i00 = y0.astype(np.int32)
        i00 *= w
        i00 += x0.astype(np.int32)
        i00 = i00.ravel()
        g00 = np.take(flat, i00, axis=0).reshape(h, w, c)
        i00 += 1
        g01 = np.take(flat, i00, axis=0).reshape(h, w, c)
        i00 += w - 1
        g10 = np.take(flat, i00, axis=0).reshape(h, w, c)
        i00 += 1
        g11 = np.take(flat, i00, axis=0).reshape(h, w, c)
        g01 -= g00
        g01 *= wx
        g01 += g00  # top
        g11 -= g10
        g11 *= wx
        g11 += g10  # bot
        g11 -= g01
        g11 *= wy
        g11 += g01
        out[...] = g11
    if invalid_any:
        out[~valid] = 0


def rotate_batch(batch: Dict[str, np.ndarray], angles_deg: np.ndarray,
                 out: Optional[Dict[str, np.ndarray]] = None
                 ) -> Dict[str, np.ndarray]:
    """Whole-batch :func:`apply_rotate`: one affine coordinate map per
    image (float64, matching scipy's internal precision) shared by
    every key, then a flat gather — bilinear for image/depth, nearest
    for the binary mask, zero fill.  ≤1e-5 from the scipy reference for
    bilinear, exact for nearest (tests/test_data_plane.py).

    Images are processed one at a time over cached [H,W] grids — the
    per-image working set fits cache, where one giant [B,H,W] gather
    thrashes — but each step is pure C-speed numpy, no scipy call.
    ``out`` buffers (ring slots) are written in place when given; keys
    absent from the batch are ignored.  |angle| < 1e-6 rows are copied
    through unchanged (the scalar path's identity short-circuit).
    """
    keys = [(k, o) for k, o in (("image", 1), ("depth", 1), ("mask", 0))
            if k in batch]
    if not keys:
        return batch
    b, h, w = batch[keys[0][0]].shape[:3]
    if out is None:
        out = {k: np.empty_like(batch[k]) for k, _ in keys}
    yy, xx = _grid(h, w)
    cy, cx = (h - 1) / 2.0, (w - 1) / 2.0
    # scipy.ndimage.rotate's trig: degree-native cosdg/sindg are EXACT
    # at the quadrant angles (cosdg(90) == 0.0), so the source
    # coordinates below match scipy's to the last bit there — with
    # np.cos(deg2rad(90)) ≈ 6e-17 boundary pixels flip validity
    # against the scalar reference.
    from scipy.special import cosdg, sindg

    for j in range(b):
        a = float(angles_deg[j])
        if abs(a) < 1e-6:
            for k, _ in keys:
                if out[k] is not batch[k]:
                    out[k][j] = batch[k][j]
            continue
        cos, sin = float(cosdg(a)), float(sindg(a))
        # Same association as scipy's affine_transform inner loop:
        # (M[h,0]*y + M[h,1]*x) + offset[h], offset = c_in - M @ c_out.
        off_y = cy - (cos * cy + sin * cx)
        off_x = cx - (-sin * cy + cos * cx)
        sy = cos * yy
        sy += sin * xx
        sy += off_y
        sx = -sin * yy
        sx += cos * xx
        sx += off_x
        valid = (sy >= 0) & (sy <= h - 1) & (sx >= 0) & (sx <= w - 1)
        invalid_any = not valid.all()
        np.clip(sy, 0, h - 1, out=sy)
        np.clip(sx, 0, w - 1, out=sx)
        for k, order in keys:
            # With out[k] aliasing batch[k] (in-place ring reuse) the
            # gather must read the pre-rotation pixels — copy the one
            # source image, not the whole batch.
            arr = batch[k][j]
            if out[k] is batch[k]:
                arr = arr.copy()
            _rotate_gather(arr, sy, sx, valid, invalid_any, order,
                           out[k][j])
    for k, _ in keys:
        batch[k] = out[k]
    return batch


def augment_batch(batch: Dict[str, np.ndarray], idxs: Sequence[int],
                  aug_seed: int, *, hflip: bool, rotate_degrees: float,
                  color_jitter: float = 0.0, norm_mean=None, norm_std=None,
                  skip_hflip: bool = False,
                  reuse_buffers: bool = False) -> Dict[str, np.ndarray]:
    """The full deterministic augmentation, whole-batch vectorized:
    jitter → hflip → rotation, same order and same per-``(aug_seed,
    idx)`` draw streams as :func:`augment_sample` applied per row.

    Callers hand in freshly assembled buffers or ring slots, never
    dataset-owned memory.  With ``reuse_buffers`` every stage writes
    back into the arrays already in ``batch`` (ring-slot discipline:
    the dict keeps its identity and its buffers); without it, stages
    may swap in fresh arrays.  ``skip_hflip`` is for backends that
    already flipped upstream (the C++ native decode) — the draws are
    consumed there, not re-applied here.
    """
    for k in ("image", "mask", "depth"):
        # Some execution layers (grain worker shared memory) hand back
        # read-only arrays; the stages below mutate rows in place.
        if k in batch and not batch[k].flags.writeable:
            batch[k] = batch[k].copy()
    if color_jitter:
        batch["image"] = apply_color_jitter_batch(
            batch["image"], jitter_draw_batch(aug_seed, idxs, color_jitter),
            norm_mean, norm_std,
            out=batch["image"] if reuse_buffers else None)
    if hflip and not skip_hflip:
        apply_hflip_batch(batch, hflip_draw_batch(aug_seed, idxs))
    if rotate_degrees:
        batch = rotate_batch(
            batch, rotate_draw_batch(aug_seed, idxs, rotate_degrees),
            out={k: batch[k] for k in ("image", "depth", "mask")
                 if k in batch} if reuse_buffers else None)
    return batch
