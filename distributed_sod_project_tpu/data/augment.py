"""Shared train-time augmentations (SURVEY.md §2 C7).

The reference-era SOD training recipe augments with horizontal flips
plus small random rotations (MINet's joint transforms rotate up to
±10°).  Both are implemented here as pure functions of
``(aug_seed, sample index)`` so every backend draws identically and
mid-epoch resume replays the exact stream (data/pipeline.py contract).

Rotation runs host-side on the decoded float arrays: bilinear for
image/depth, nearest for the binary mask, constant fill — matching the
torchvision ``rotate(expand=False)`` convention.
"""

from __future__ import annotations

from typing import Dict

import numpy as np


def hflip_draw(aug_seed: int, idx: int) -> bool:
    rng = np.random.default_rng(np.random.SeedSequence([aug_seed, int(idx)]))
    return bool(rng.random() < 0.5)


def rotate_draw(aug_seed: int, idx: int, degrees: float) -> float:
    """Deterministic angle in [-degrees, +degrees] for this sample.
    A distinct stream from hflip (offset key) so the two draws stay
    independent."""
    rng = np.random.default_rng(
        np.random.SeedSequence([aug_seed ^ 0x5EED, int(idx)]))
    return float((rng.random() * 2.0 - 1.0) * degrees)


def jitter_draw(aug_seed: int, idx: int, strength: float):
    """Deterministic (brightness, saturation, contrast) factors, each
    in [1-strength, 1+strength] — a distinct stream from hflip/rotate
    (offset key) so all draws stay independent."""
    rng = np.random.default_rng(
        np.random.SeedSequence([aug_seed ^ 0xC0108, int(idx)]))
    f = 1.0 + (rng.random(3) * 2.0 - 1.0) * strength
    return float(f[0]), float(f[1]), float(f[2])


_LUMA = np.asarray([0.299, 0.587, 0.114], np.float32)


def apply_color_jitter(sample: Dict[str, np.ndarray], factors,
                       mean, std) -> Dict[str, np.ndarray]:
    """Brightness → saturation → contrast on the IMAGE only (masks and
    depth untouched), computed in the unnormalized [0, 1] space (the
    sample arrives mean/std-normalized) and clipped back to the data
    range — the torchvision ColorJitter semantics with a fixed
    application order so every backend agrees bit-for-bit.

    Applied BEFORE rotation: contrast normalizes around the gray mean,
    and rotation's zero-fill corners would shift that statistic.
    """
    b, s, c = factors
    mean = np.asarray(mean if mean is not None else 0.0, np.float32)
    std = np.asarray(std if std is not None else 1.0, np.float32)
    img = sample["image"].astype(np.float32)
    raw = img * std + mean
    raw = raw * b
    gray = (raw @ _LUMA)[..., None]
    raw = gray + (raw - gray) * s
    gmean = np.float32(gray.mean())
    raw = gmean + (raw - gmean) * c
    raw = np.clip(raw, 0.0, 1.0)
    out = dict(sample)
    out["image"] = ((raw - mean) / std).astype(sample["image"].dtype)
    return out


def apply_hflip(sample: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    out = dict(sample)
    for k in ("image", "mask", "depth"):
        if k in out:
            out[k] = np.ascontiguousarray(out[k][:, ::-1])
    return out


def apply_rotate(sample: Dict[str, np.ndarray],
                 angle_deg: float) -> Dict[str, np.ndarray]:
    """Rotate image/depth bilinearly and the mask nearest by
    ``angle_deg`` about the center, same spatial shape (expand=False)."""
    if abs(angle_deg) < 1e-6:
        return sample
    from scipy import ndimage

    out = dict(sample)
    for k, order in (("image", 1), ("depth", 1), ("mask", 0)):
        if k in out:
            arr = out[k]
            rot = ndimage.rotate(arr, angle_deg, axes=(1, 0),
                                 reshape=False, order=order,
                                 mode="constant", cval=0.0)
            out[k] = np.ascontiguousarray(rot.astype(arr.dtype))
    return out


def augment_sample(sample: Dict[str, np.ndarray], idx: int, aug_seed: int,
                   *, hflip: bool, rotate_degrees: float,
                   color_jitter: float = 0.0, norm_mean=None, norm_std=None
                   ) -> Dict[str, np.ndarray]:
    """The full deterministic train-time augmentation for one sample:
    color jitter (photometric, image only) → hflip → rotation."""
    if color_jitter:
        sample = apply_color_jitter(
            sample, jitter_draw(aug_seed, idx, color_jitter),
            norm_mean, norm_std)
    if hflip and hflip_draw(aug_seed, idx):
        sample = apply_hflip(sample)
    if rotate_degrees:
        sample = apply_rotate(sample, rotate_draw(aug_seed, idx,
                                                  rotate_degrees))
    return sample
