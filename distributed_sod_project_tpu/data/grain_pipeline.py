"""Grain input backend — the third loader, same contract as the rest.

Google Grain is the TPU-era input library (deterministic, multiprocess,
checkpointable iterators).  This backend keeps OUR sharding semantics —
one global permutation per epoch, each host taking its contiguous slice
of every global batch (identical batch composition to the host/tfdata
backends, verified in tests) — and uses Grain for the execution layer:
worker processes, prefetch, and batch assembly.  Select with
``--set data.backend=grain``.

The epoch's record sequence for this host is precomputed as an index
view (pure function of (seed, epoch), like the other backends), so
``skip_steps`` mid-epoch resume is an index offset here too.
"""

from __future__ import annotations

from typing import Dict, Iterator

import numpy as np


class _ShardView:
    """Random-access view: position in this host's epoch sequence →
    RAW decoded sample.  Augmentation happens on the assembled batch in
    the parent (the shared vectorized path in data/augment.py), so
    Grain's worker processes carry only the decode."""

    def __init__(self, dataset, keys: np.ndarray):
        self._dataset = dataset
        self._keys = keys

    def __len__(self) -> int:
        return len(self._keys)

    def __getitem__(self, i) -> Dict[str, np.ndarray]:
        return dict(self._dataset[int(self._keys[int(i)])])


class GrainLoader:
    """HostDataLoader-compatible loader executed by Grain."""

    def __init__(
        self,
        dataset,
        global_batch_size: int,
        shard_id: int = 0,
        num_shards: int = 1,
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = True,
        hflip: bool = False,
        rotate_degrees: float = 0.0,
        color_jitter: float = 0.0,
        num_workers: int = 0,
    ):
        if global_batch_size % num_shards != 0:
            raise ValueError(
                f"global_batch_size={global_batch_size} not divisible by "
                f"num_shards={num_shards}")
        self.rotate_degrees = float(rotate_degrees)
        self.color_jitter = float(color_jitter)
        self.dataset = dataset
        self.global_batch_size = global_batch_size
        self.local_batch_size = global_batch_size // num_shards
        self.shard_id = shard_id
        self.num_shards = num_shards
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.hflip = hflip
        self.num_workers = num_workers
        self._epoch = 0
        self._skip = 0

    def set_epoch(self, epoch: int) -> None:
        self._epoch = epoch

    def skip_steps(self, n: int) -> None:
        """One-shot mid-epoch resume offset (see HostDataLoader)."""
        self._skip = int(n)

    @property
    def steps_per_epoch(self) -> int:
        n = len(self.dataset)
        if self.drop_last:
            return n // self.global_batch_size
        return -(-n // self.global_batch_size)

    def _epoch_order(self, epoch: int) -> np.ndarray:
        n = len(self.dataset)
        if self.shuffle:
            rng = np.random.default_rng(
                np.random.SeedSequence([self.seed, epoch]))
            order = rng.permutation(n)
        else:
            order = np.arange(n)
        if not self.drop_last and n % self.global_batch_size:
            pad = self.global_batch_size - n % self.global_batch_size
            order = np.concatenate([order, order[:pad]])
        return order

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        import grain.python as grain

        epoch = self._epoch
        start, self._skip = self._skip, 0
        order = self._epoch_order(epoch)
        steps = self.steps_per_epoch
        aug_seed = hash((self.seed, epoch)) & 0x7FFFFFFF

        # This host's contiguous slice of every remaining global batch.
        keys = (np.concatenate([
            order[s * self.global_batch_size
                  + self.shard_id * self.local_batch_size:
                  s * self.global_batch_size
                  + (self.shard_id + 1) * self.local_batch_size]
            for s in range(start, steps)]) if steps > start
            else np.zeros((0,), np.int64))
        if not len(keys):
            return iter(())

        view = _ShardView(self.dataset, keys)
        sampler = grain.IndexSampler(
            num_records=len(view),
            shard_options=grain.NoSharding(),  # host sharding is in `keys`
            shuffle=False,  # order is already the epoch permutation
            num_epochs=1,
            seed=self.seed,
        )
        loader = grain.DataLoader(
            data_source=view,
            sampler=sampler,
            operations=[grain.Batch(self.local_batch_size,
                                    drop_remainder=True)],
            worker_count=self.num_workers,
        )

        def batches():
            from .augment import augment_batch

            mean = getattr(self.dataset, "mean", None)
            std = getattr(self.dataset, "std", None)
            for batch in loader:
                # Grain assembled fresh arrays; the shared vectorized
                # augment (same per-(aug_seed, idx) draws as every
                # backend) runs batch-level in the parent.
                yield augment_batch(
                    dict(batch), batch["index"], aug_seed,
                    hflip=self.hflip,
                    rotate_degrees=self.rotate_degrees,
                    color_jitter=self.color_jitter,
                    norm_mean=mean, norm_std=std)

        return batches()
