"""Experiment configuration system.

Capability parity: SURVEY.md §2 C13 (per-experiment config dicts +
dataset path registry in the reference's ``config/``).  Re-designed as
typed, frozen dataclasses so a config can be hashed into a jit cache key
and serialized into a checkpoint for exact-resume.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class DataConfig:
    """Input pipeline configuration (SURVEY.md §2 C7)."""

    dataset: str = "synthetic"  # synthetic | duts | nju2k | nlpr
    backend: str = "host"  # host (C++/PIL loader) | tfdata | grain
    root: Optional[str] = None  # directory with <name>-Image/ and <name>-Mask/
    val_root: Optional[str] = None  # held-out set for in-training eval
    image_size: Tuple[int, int] = (320, 320)  # H, W — static for XLA
    use_depth: bool = False  # RGB-D datasets carry a depth channel
    hflip: bool = True
    # ColorJitter-style photometric aug: brightness/saturation/contrast
    # factors each drawn in [1-s, 1+s] per sample (0 disables; image
    # only, identical across backends via data/augment.py draws).
    color_jitter: float = 0.0
    rotate_degrees: float = 0.0  # ±deg random rotation (MINet-style
    #   aug); identical per-index draws on every backend
    normalize_mean: Tuple[float, float, float] = (0.485, 0.456, 0.406)
    normalize_std: Tuple[float, float, float] = (0.229, 0.224, 0.225)
    num_workers: int = 4  # host backend: parallel batch-BUILD threads
    #   (each assembles+augments a whole batch; decode may additionally
    #   go to processes, see decode_procs)
    prefetch_batches: int = 2
    # Host-backend data-plane knobs (docs/PERFORMANCE.md "Host data
    # plane").  lookahead: batches built ahead of the consumer (in
    # flight across the build workers).
    lookahead: int = 2
    # >0: recycle this many preallocated batch buffers instead of
    # allocating per step (zero-copy assembly).  CONTRACT: a yielded
    # batch's arrays are overwritten after 2 further batches have been
    # yielded — consumers that hold batches longer must copy.  The
    # train/bench paths consume immediately; keep 0 (fresh arrays)
    # when iterating by hand.
    ring_buffers: int = 0
    # >0: decode samples in this many worker PROCESSES writing into
    # shared-memory ring slots — sidesteps the GIL for the PIL decode
    # path when native/ is unbuilt (implies a ring).  0 = in-thread.
    decode_procs: int = 0
    # Raw-decoded-sample cache (the tf.data cache() analogue): -1 =
    # auto (cache every sample when the whole dataset fits
    # cache_budget_mb of host RAM), 0 = off, N = cache at most N
    # samples.  Epochs after the first cost a row copy per sample
    # instead of a decode; augmentation still runs per epoch, so the
    # (seed, epoch, idx) draw contract is untouched.
    cache_decoded: int = -1
    cache_budget_mb: int = 1024
    transfer_dtype: str = "float32"  # bfloat16 halves H2D image bytes
    synthetic_size: int = 256  # virtual dataset length when dataset=synthetic
    # Multi-scale training (MINet-style): the cycle of square train
    # sizes, e.g. (256, 320, 384).  Empty = single-scale at image_size.
    # Each size is one statically-shaped compiled step (XLA-friendly);
    # the resize rides the device, not the input pipeline.  Use
    # multiples of 32 (backbone strides + fused-loss lane alignment).
    multiscale: Tuple[int, ...] = ()
    # >0: re-run the cheap non-finite batch check every N batches (the
    # first batch is always fully validated); 0 keeps the once-only
    # behavior.  Catches mid-run data corruption before it becomes an
    # unexplained divergence (utils/checks.py).
    validate_every: int = 0
    # >0: tolerate this many corrupt samples per run — each is skipped
    # (deterministic next-index substitution) and counted into the
    # `data_skipped` metric instead of killing the epoch; budget
    # exhaustion raises.  0 = fail on the first corrupt sample.
    # See resilience/dataguard.py and docs/RESILIENCE.md.
    skip_budget: int = 0


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    """Model zoo selection (SURVEY.md §2 C5/C6)."""

    name: str = "minet"  # minet | hdfnet | u2net | basnet | swin_sod
    backbone: str = "vgg16"  # vgg16 | resnet50 | swin_t | none (u2net is self-contained)
    backbone_bn: bool = True  # False → classic torchvision VGG16 layout
    #   (the tree ImageNet weight porting targets; see backbones/vgg.py)
    out_stride: int = 1  # saliency logits at input resolution
    sync_bn: bool = True  # cross-replica BatchNorm stats over the data axis
    bn_momentum: float = 0.9
    compute_dtype: str = "bfloat16"  # MXU-native; params stay float32
    param_dtype: str = "float32"
    remat: bool = False  # jax.checkpoint the forward (train step)
    # What remat SAVES (only read when remat=true): "none" recomputes
    # everything (min memory, +~1/3 FLOPs); "dots" keeps matmul/conv
    # outputs and recomputes elementwise (the usual best-MFU
    # compromise); "dots_no_batch" keeps only batch-free dots (weights'
    # contractions).  A/B on hardware via bench.py --set.
    remat_policy: str = "none"  # none | dots | dots_no_batch
    # Attention core for the transformer zoo member (vit_sod only):
    # "xla" materializes the score matrix, "flash" runs the Pallas
    # tiled-softmax kernel (pallas/flash_attention.py) — required for
    # high-resolution single-chip work where N² scores exceed HBM.
    attn_impl: str = "xla"  # xla | flash
    # Dynamic-local-filter core (hdfnet only): "xla" = im2col+einsum,
    # "pallas" = fused VMEM shifted-FMA kernel
    # (pallas/dynamic_filter.py) — no ksize²-wide patch tensor in HBM.
    dlf_impl: str = "xla"  # xla | pallas
    # Decoder resample strategy (minet / hdfnet / gatenet / u2net —
    # the four decoder users of the upsample+merge idiom).  Subsumes
    # the DSOD_RESIZE_IMPL env knob (env still honored at the default
    # for the recorded A/B legs; an explicit non-default value wins):
    #   fast  — slice/lerp fast paths, layout-stable interleave
    #           (default; all-XLA, jax.image.resize-exact)
    #   xla   — force the generic jax.image.resize (A/B escape hatch)
    #   convt — 2x upsamples as depthwise fractionally-strided convs
    #   fused — Pallas fused resample-merge (pallas/fused_resample.py):
    #           upsample + add/concat as ONE VMEM pass per image.
    #           Knob-gated pending a hardware A/B win (the pre-committed
    #           non-XLA-default rule; legs in tools/tpu_agenda_r5.sh).
    resample_impl: str = "fast"  # fast | xla | convt | fused
    # Conv-block execution strategy (minet / hdfnet / gatenet / u2net —
    # every ConvBNAct in the four decoder families AND their VGG/ResNet
    # backbones routes through the one models/layers.py seam):
    #   xla   — nn.Conv + nn.BatchNorm (default; the lowered program is
    #           byte-identical to the pre-knob tree)
    #   fused — Pallas fused conv-stage kernel (pallas/fused_conv.py):
    #           conv + inference-mode BN + ReLU as ONE VMEM pass per
    #           image; list inputs convolve as their channel concat
    #           without materializing it (decoder heads); train-mode
    #           BN sites keep flax's BatchNorm after the fused conv;
    #           out-of-envelope sites (stride>1, even kernels, VMEM
    #           budget) fall back per-site.  Composes with the serve
    #           precision arms (int8/fp8 weights dequantize in-kernel).
    #           Knob-gated pending a hardware A/B win (the pre-committed
    #           non-XLA-default rule; legs in tools/tpu_agenda_r14.sh).
    conv_impl: str = "xla"  # xla | fused
    pretrained: Optional[str] = None  # .npz from tools/port_torch_weights.py
    # Structural deep supervision for models where aux heads are
    # optional add-ons (vit_sod's mid-depth head).  U²-Net/BASNet side
    # outputs are integral to their architectures and ignore this.
    # LossConfig.deep_supervision separately gates which returned
    # outputs the loss consumes.
    deep_supervision: bool = True


@dataclasses.dataclass(frozen=True)
class LossConfig:
    """Loss weighting (SURVEY.md §2 C8)."""

    bce: float = 1.0
    iou: float = 1.0
    ssim: float = 1.0
    cel: float = 0.0  # MINet's consistency-enhanced loss
    ssim_window: int = 11
    deep_supervision: bool = True  # sum loss over every side output
    fused_kernel: bool = False  # route through the Pallas fused loss


@dataclasses.dataclass(frozen=True)
class OptimConfig:
    """Optimizer + schedule (SURVEY.md §2 C9)."""

    optimizer: str = "sgd"  # sgd | adamw | lars (large-batch)
    lr: float = 0.005
    momentum: float = 0.9
    weight_decay: float = 5e-4
    nesterov: bool = True
    schedule: str = "poly"  # poly | cosine | constant
    poly_power: float = 0.9
    warmup_steps: int = 0
    grad_clip_norm: float = 0.0  # 0 disables
    # Layer-wise LR decay for transformer fine-tuning (BEiT-style):
    # heads at full LR, encoder block i at decay^(n_blocks+1-(i+1)),
    # the patch/pos embedding deepest.  1.0 disables (from-scratch).
    layer_decay: float = 1.0
    accum_steps: int = 1  # >1: optax.MultiSteps gradient accumulation
    ema_decay: float = 0.0  # >0: track an EMA of params; eval uses it
    # >0: skip updates whose gradients are non-finite (bad batch / bf16
    # overflow) instead of poisoning the params; the train loop raises
    # once this many CONSECUTIVE skips accumulate (a persistent
    # divergence, not a glitch), checked at the logging cadence.  A bad
    # update is NEVER applied.
    skip_nonfinite: int = 0
    # ZeRO-1-style cross-replica weight-update sharding (PAPERS.md:
    # arXiv 2004.13336): optimizer/EMA buffers shard over the data axis,
    # grads reduce-scatter into a 1/N-sized update, params all-gather.
    # Routes training through the GSPMD step (needs model.sync_bn=False;
    # BN stats are global-batch there by construction).
    zero1: bool = False


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Device mesh layout (SURVEY.md §2.3).

    The load-bearing axis is ``data`` (DP parity with the reference's
    DDP/NCCL).  ``model`` shards attention heads / wide dense layers for
    the Swin path; ``seq`` is the ring-attention sequence-parallel axis.
    Axis size ``-1`` means "all remaining devices".
    """

    data: int = -1
    model: int = 1
    seq: int = 1
    # Sequence-parallel strategy over the ``seq`` axis: 'ring' rotates
    # K/V blocks (ppermute; any head count), 'ulysses' redistributes
    # heads with two all-to-alls (needs model heads % seq == 0; lower
    # collective latency, full-sequence tiles for the flash kernel).
    sp_strategy: str = "ring"  # ring | ulysses
    # Two-level data-axis hierarchy for pod-scale meshes: the ``data``
    # axis factors as (data_hosts, chips_per_host) with consecutive
    # device ids on the same host (the make_mesh layout guarantees
    # this).  >1 routes each gradient bucket's psum through intra-host
    # reduce-scatter -> inter-host all-reduce on 1/chips_per_host of
    # the bytes -> intra-host all-gather, so the slow DCN hop carries
    # only a 1/chips_per_host segment (docs/MULTIHOST.md "Hierarchical
    # collectives").  Must divide the data axis size.
    data_hosts: int = 1


@dataclasses.dataclass(frozen=True)
class ParallelConfig:
    """The unified partition-rule sharding engine (parallel/rules.py +
    parallel/engine.py; docs/MULTIHOST.md "Rule presets").

    ``engine='rules'`` routes training through ONE rule-driven step
    builder: DP, TP, SP, and FSDP are partition-rule presets on the
    same traced root.  The rules engine shipped bitwise-proven against
    the legacy builders in round 17 and the default flipped in round 18
    per the bit-identical flip rule; the legacy builders are deleted —
    'rules' is the only engine.
    """

    engine: str = "rules"  # rules (the legacy builders were removed)
    # Preset selection: 'auto' derives the preset from the mesh (seq>1
    # -> sp, model>1 or zero -> tp/gspmd, else dp).  'fsdp' is the only
    # value that cannot be derived: params themselves shard over
    # ``data`` (fsdp_fallback_rule picks each leaf's largest divisible
    # dim), the partitioner all-gathers them just-in-time per layer in
    # forward/backward and reduce-scatters grads — full ZeRO-3-style
    # sharding as pure config.  Requires model.sync_bn=false (GSPMD
    # path, no named axis) and mesh.model == mesh.seq == 1.
    preset: str = "auto"  # auto | dp | tp | sp | fsdp
    # ZeRO-style cross-replica weight-update sharding (PAPERS.md: arXiv
    # 2004.13336), the rules-engine generalization of optim.zero1:
    #   0 — off (replicated optimizer state)
    #   1 — optimizer moments + EMA shard over ``data``; grads reduce-
    #       scatter into 1/N-sized updates, params all-gather
    #   2 — additionally pins the gradient tree to the sharded layout
    #       (with_sharding_constraint), so the full replicated gradient
    #       tree is never materialized between reduce and update
    # Routes through the GSPMD preset (needs model.sync_bn=false, same
    # contract as optim.zero1).  Per-device HBM saving is reported via
    # the capacity ledger (dsod_capacity_comm_zero_hbm_saved_bytes).
    zero: int = 0
    # Bucketed, backward-ordered gradient allreduce (DP preset only):
    # grads partition into size-targeted buckets — latest-layer grads
    # (first available in the backward pass) reduce first — and each
    # bucket is its own ``lax.psum``, so early buckets' communication
    # can overlap remaining backward compute.  0 = one monolithic
    # reduce (the legacy program).  Per-element arithmetic is identical
    # (psum/n exactly as lax.pmean computes it) — bitwise-asserted vs
    # monolithic in tests/test_sharding_rules.py.  No-op on the GSPMD
    # preset (the partitioner schedules its own collectives).
    comm_bucket_mb: float = 25.0
    # Gradient compression arm for the bucketed allreduce: 'bf16' casts
    # each bucket to bfloat16 for the wire and back to f32 after —
    # halves gradient comm bytes, NOT bitwise.  'int8_ef' symmetrically
    # quantizes each bucket to int8 against a shared global scale
    # (lax.pmax of per-replica amax, so the integer psum is exact) and
    # carries the quantization error in a persistent error-feedback
    # residual in the train state (sharded by the ZeRO specs), added
    # back into the next step's buffer — 1 B/elem achievable wire,
    # quality-gated exactly like bf16.  Both gated the precision_gate
    # way: tools/grad_comm_gate.py keeps a checked-in delta baseline
    # (tools/grad_comm_baseline.json).
    grad_compression: str = "none"  # none | bf16 | int8_ef
    # Raise on params the rule table does not match (instead of the
    # replicate-by-default fallback) — debugging aid when authoring
    # rules for a new backbone.
    rules_strict: bool = False


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Online serving (serve/ subsystem — docs/SERVING.md).

    The engine coalesces arbitrary-time, arbitrary-size requests into
    the fixed-shape compiled programs evaluation already uses: one AOT-
    compiled forward per (resolution bucket, batch bucket), requests
    grouped per resolution bucket and padded up to the smallest batch
    bucket that fits.  All knobs here are request-plane policy; nothing
    below changes a compiled program's math.
    """

    host: str = "127.0.0.1"
    port: int = 8080  # tools/serve.py --port 0 binds an ephemeral port
    # Static batch shapes compiled at startup (ascending).  A dispatch
    # takes the smallest bucket >= the coalesced group, zero-padding
    # the remainder (eval/inference.py::pad_to_batch).
    batch_buckets: Tuple[int, ...] = (1, 4, 8)
    # Static square resolutions compiled at startup.  Empty = one
    # bucket at max(data.image_size).  A request resizes to the
    # smallest bucket >= its longest side (largest bucket otherwise);
    # degraded mode forces the smallest.
    resolution_buckets: Tuple[int, ...] = ()
    # Precision arms (serve/precision.py; docs/SERVING.md "Precision
    # arms").  Every arm in precision_arms gets its own cast-on-load
    # weight view and its own AOT-compiled program per (res, batch)
    # bucket at startup; `precision` picks the arm requests serve at by
    # default (X-Precision overrides per request, within the enabled
    # set).  Arms: f32 (identity — bitwise the offline eval path),
    # bf16 (weights cast to bfloat16: half the weight HBM), int8 / fp8
    # (8-bit weight-only per-channel quantization, dequantized inside
    # the compiled program; fp8 only where jaxlib has float8_e4m3fn).
    # The degraded ladder steps DOWN through the enabled arms before it
    # touches resolution; quality deltas per arm are measured and
    # budgeted by tools/precision_gate.py.
    precision: str = "f32"
    precision_arms: Tuple[str, ...] = ("f32", "bf16")
    # How long the oldest queued request may wait for co-riders before
    # its batch dispatches anyway (the latency/occupancy trade).
    max_wait_ms: float = 5.0
    max_queue: int = 64  # admission bound; beyond it requests shed (429)
    max_inflight: int = 2  # device batches dispatched but not fetched
    post_workers: int = 2  # host pool for original-resolution resize-back
    # Default per-request deadline (0 = none; X-SLO-MS overrides).  A
    # request that can no longer meet its deadline — now + the res
    # bucket's EWMA device time exceeds it — is shed BEFORE the forward.
    slo_ms: float = 0.0
    request_timeout_s: float = 30.0  # HTTP handler wait on the future
    tta: bool = False  # horizontal-flip TTA (2x forward; off when degraded)
    # >0: watch the checkpoint directory and hot-swap weights between
    # dispatches when a newer VALID step appears (restore-latest-VALID
    # via the integrity layer; swaps are atomic w.r.t. /predict).
    reload_poll_s: float = 0.0
    # Dispatch-loop heartbeat deadline feeding /healthz (resilience/
    # watchdog.py).  A wedged device dispatch stops the beat; /healthz
    # flips 503 so the fronting LB drains this replica.  0 = off.
    watchdog_deadline_s: float = 60.0
    # Degraded-mode hysteresis LADDER: each rung engages after queue
    # depth has stayed >= degraded_high * max_queue for
    # degraded_engage_s, and unwinds (one rung at a time, reverse
    # order) after it has stayed <= degraded_low * max_queue for
    # degraded_disengage_s.  Rungs step PRECISION down through the
    # enabled precision_arms first (TTA off from rung 1), and only the
    # final rung forces the smallest resolution bucket; responses
    # self-report the rung (X-Degraded: <level>).
    degraded_high: float = 0.75
    degraded_low: float = 0.25
    degraded_engage_s: float = 2.0
    degraded_disengage_s: float = 5.0
    # End-to-end tracing (utils/tracing.py; docs/OBSERVABILITY.md).
    # trace_sample is the fraction of requests whose span timelines are
    # recorded (deterministic in the request id, so a router and its
    # replicas trace the SAME requests); 0 disables tracing entirely —
    # /metrics output is then byte-identical to the pre-tracing
    # rendering.  The X-Timing response header rides every 200
    # regardless (it is computed from numbers the engine already
    # tracks).  trace_capacity bounds the in-memory ring of completed
    # traces; trace_worst_n pins the slowest N traces per
    # (model, res bucket) as exemplars that survive the ring.
    trace_sample: float = 0.01
    trace_capacity: int = 256
    trace_worst_n: int = 4
    # -- model-health quality/drift monitors (serve/quality.py;
    #    docs/OBSERVABILITY.md "Model health").  All OFF by default:
    #    with quality_monitor=false the request hot path pays nothing
    #    and /metrics is byte-identical to the monitor-less rendering.
    # Master switch: per-request output statistics (foreground
    # fraction, mean confidence, boundary entropy) + input/output
    # drift histograms with PSI vs a checked-in reference
    # (tools/quality_reference.json), under model=/arm= labels.
    quality_monitor: bool = False
    # Fraction of non-f32 responses re-scored on the f32 reference arm
    # (shadow scoring): live arm-vs-f32 disagreement gauges turn the
    # offline tools/precision_gate.py budget into a continuous online
    # check.  Deterministic counter sampling; requires "f32" among
    # precision_arms; shadow forwards run on a bounded side lane and
    # DROP (counted) rather than queue behind live traffic.
    quality_shadow_sample: float = 0.0
    # Reference-histogram file for PSI drift ("" = the checked-in
    # tools/quality_reference.json when it has an entry for this
    # model; no reference = drift gauges idle, stats still collected).
    quality_reference: str = ""
    # Default alert budgets (utils/alerts.py; wired when the monitor
    # is on): shadow mean-abs-disagreement budget, PSI drift bound,
    # and the hysteresis dwells of the built-in quality rules.
    quality_shadow_budget: float = 0.02
    quality_psi_threshold: float = 0.25
    # Minimum online-histogram observations before a PSI verdict is
    # rendered at all: one request is not drift evidence, and an
    # unwarmed histogram scored against a reference reads as a huge
    # (false) shift.  Below the floor the drift gauges stay absent
    # and quality_psi_max reports 0 (no verdict).
    quality_psi_min_count: int = 64
    quality_alert_for_s: float = 5.0
    quality_alert_clear_s: float = 10.0
    # Extra alert rules, colon DSL ("name:signal:kind:value[:for[:clear]]"
    # — comma-free so --set tuple coercion passes them through); they
    # join the built-in quality rules when the monitor is on.
    alert_rules: Tuple[str, ...] = ()
    # -- capacity & SLO observability (utils/capacity.py, utils/slo.py;
    #    docs/OBSERVABILITY.md "Capacity & SLO").  Both OFF by default:
    #    /metrics stays byte-identical to the ledger-less rendering.
    # Live per-compiled-program cost ledger: at AOT warmup every cached
    # executable's cost_analysis()/memory_analysis() is recorded, and
    # the per-(res,batch,arm) EWMA device time turns it into live
    # MFU / roofline-utilization / HBM gauges (dsod_capacity_*), plus a
    # device-vs-queue-vs-host stage-share attribution gauge derived
    # from the PR-9 stage splits — the scale-out-vs-futile signal.
    capacity_ledger: bool = False
    # Declarative SLO objectives, colon DSL (comma-free):
    #   name:scope:kind:goal:window_s[:latency_ms]
    #   scope = all | model=NAME | tenant=NAME
    #   kind  = availability (good = served ok)
    #         | latency      (good = served ok within latency_ms)
    # e.g. "avail:all:availability:0.999:3600"
    #      "fast:all:latency:0.95:3600:250"
    # Empty = off.  Non-empty arms sliding-window error-budget
    # accounting + multi-window burn rates (dsod_slo_* families, the
    # /slo endpoint) fed by the server's own terminal outcomes;
    # burn-rate/budget rules ride the alert engine and degrade
    # /healthz on budget exhaustion.
    slo_objectives: Tuple[str, ...] = ()
    # Burn-rate alert threshold: the rule fires when BOTH the fast
    # (window/12) and slow (full-window) burn rates exceed it (the
    # multi-window AND — min of the two windows is the signal).
    slo_burn_threshold: float = 10.0
    # Hysteresis dwells of the built-in SLO rules (alert-engine
    # semantics: breach for_s before firing, clear clear_s to resolve).
    slo_alert_for_s: float = 5.0
    slo_alert_clear_s: float = 60.0
    # -- black-box flight recorder (utils/flightrecorder.py;
    #    docs/OBSERVABILITY.md "Flight recorder & incidents").  OFF by
    #    default: no thread, no files, /metrics byte-identical.  On,
    #    a background thread samples this engine's telemetry registry
    #    every recorder_sample_s into a bounded on-disk ring of
    #    append-only JSONL segments (recorder_dir REQUIRED — loud
    #    ValueError otherwise), records typed events (hot reloads,
    #    degraded-ladder moves, alert transitions, dispatch errors),
    #    and on a trigger (alert firing, watchdog trip, SIGTERM,
    #    dispatch crash) snapshots the last recorder_bundle_window_s of
    #    the ring + live sections (/debug/traces, /alerts, /slo,
    #    capacity, resolved config) into one gzip incident bundle under
    #    <recorder_dir>/incidents/ — debounced by recorder_debounce_s
    #    so a flapping alert cannot bundle-storm.  The ring survives
    #    SIGKILL (torn-tail-tolerant reader; tools/fleet_chaos.py
    #    proves the replay) and tools/incident.py post-mortems it.
    flight_recorder: bool = False
    recorder_dir: str = ""
    recorder_sample_s: float = 1.0
    recorder_segment_kb: int = 256
    recorder_keep_segments: int = 16
    recorder_bundle_window_s: float = 300.0
    recorder_debounce_s: float = 30.0


@dataclasses.dataclass(frozen=True)
class FleetTenantConfig:
    """One tenant class for multi-tenant fleet admission (serve/router.py).

    ``priority`` orders tenants into shed classes under backlog: when a
    target replica's queue is past a class's backlog fraction, that
    class sheds at the ROUTER (429) while higher classes still admit —
    the fraction for a class is ``(rank+1) / n_classes`` over the
    distinct priorities in the fleet (the highest class never priority-
    sheds before the engine's own queue bound).  ``rate_rps``/``burst``
    arm a token-bucket budget (requests/s sustained, ``burst`` capacity
    — defaults to ``rate_rps`` when 0); ``rate_rps=0`` means unlimited.
    Budgets are enforced at the router door, BEFORE a request ever
    reaches an engine queue.
    """

    name: str = "default"
    priority: int = 0
    rate_rps: float = 0.0
    burst: float = 0.0


@dataclasses.dataclass(frozen=True)
class FleetModelConfig:
    """One fleet member: a routing key plus exactly one backend source.

    - ``config`` (registered experiment name) → in-process engine with
      randomly-initialised weights (smoke/bench posture);
    - ``ckpt_dir`` → in-process engine serving that checkpoint
      (``config`` optionally overrides the sidecar config name);
    - ``url`` → remote serve process proxied as-is (its own engine owns
      admission and accounting; the router adds tenancy + aggregation).

    ``overrides`` are dotted ``section.field=value`` strings applied to
    the member's ExperimentConfig (in-process members only).
    """

    name: str = ""
    config: Optional[str] = None
    ckpt_dir: Optional[str] = None
    url: Optional[str] = None
    # N remote replicas under ONE routing key (scale-out + failover):
    # each URL becomes a RemoteBackend replica "name#i"; the router
    # spreads requests round-robin and fails over between them
    # (serve/failover.py).  Exclusive of url/config/ckpt_dir.
    urls: Tuple[str, ...] = ()
    overrides: Tuple[str, ...] = ()


@dataclasses.dataclass(frozen=True)
class FleetConfig:
    """Multi-model, multi-tenant serving fleet (serve/fleet.py +
    serve/router.py; docs/SERVING.md "Fleet").

    A router tier fronting N engine replicas: requests name a model
    (``X-Model`` header / ``model=`` query field) and a tenant
    (``X-Tenant``); the router resolves the replica (404 on unknown),
    enforces the tenant's token-bucket budget and priority class, then
    forwards.  Co-resident in-process engines share one device through
    a single interleaved dispatch loop (round-robin over per-model
    batchers, so a hot model cannot starve a cold one).
    """

    models: Tuple[FleetModelConfig, ...] = ()
    tenants: Tuple[FleetTenantConfig, ...] = ()
    # Tenant class used when a request carries no X-Tenant header (or
    # an unknown one, unless strict_tenants).  Auto-registered with
    # unlimited budget + the lowest configured priority when absent
    # from ``tenants``.
    default_tenant: str = "default"
    # True: an unknown X-Tenant is rejected 403 at the door (never
    # counted — the request does not enter the fleet accounting).
    # False (default): unknown tenants ride the default tenant's class.
    strict_tenants: bool = False
    host: str = "127.0.0.1"
    port: int = 8080
    # Router-side wait on an in-process engine future / remote response.
    request_timeout_s: float = 30.0
    # Seconds between remote-replica /healthz probes.  Probing runs on
    # a BACKGROUND thread per remote (serve/fleet.py HealthProber) —
    # the request path and the /healthz//metrics handlers only ever
    # read the cached verdict, never pay a connect timeout inline.
    health_poll_s: float = 2.0

    # -- fault tolerance (serve/failover.py; docs/SERVING.md
    #    "Failure semantics") ------------------------------------------
    # Total dispatch attempts per request (1 = no retry).  Retries fire
    # on transport failures (connect refused/reset, timeout) and remote
    # 5xx, preferring a DIFFERENT healthy replica (failover) before
    # re-trying the same one.  Every retry is charged against the
    # request's residual X-SLO-MS budget — the router forwards the
    # residual, not the original, on every attempt.
    retry_max_attempts: int = 2
    # Capped exponential backoff between attempts (base, cap; ms).
    retry_backoff_ms: float = 10.0
    retry_backoff_max_ms: float = 250.0
    # Tail-latency hedge: after this many ms without a first answer,
    # fire the SAME request at a second healthy replica; first response
    # wins, the loser is abandoned and counted.  0 = off; -1 = auto
    # (hedge at the router's observed per-model p95).  Remote replicas
    # only — an in-process engine shares the device with its siblings,
    # so a hedge there would just queue behind itself.
    hedge_ms: float = 0.0
    # Circuit breaker per replica: this many CONSECUTIVE failures open
    # it (dispatches route around the replica without paying its
    # timeout); after breaker_reset_s one half-open probe request is
    # let through and its outcome decides re-admission vs re-open.
    breaker_failures: int = 3
    breaker_reset_s: float = 5.0
    # Router-tier tracing (utils/tracing.py; docs/OBSERVABILITY.md):
    # the router mints X-Request-ID, records a child span per dispatch
    # attempt (replica + breaker state; retries and hedges share the
    # request's one trace id), and serves sampled + worst-N exemplar
    # traces at /debug/traces.  Sampling is deterministic in the
    # request id, so in-process engines (serve.trace_sample) and
    # remote replicas at the same rate trace the same requests.
    trace_sample: float = 0.01
    trace_capacity: int = 256
    trace_worst_n: int = 4

    # -- capacity & SLO observability (utils/slo.py, serve/prober.py;
    #    docs/OBSERVABILITY.md "Capacity & SLO") -----------------------
    # Router-tier SLO objectives (same colon DSL as
    # serve.slo_objectives; scope model=/tenant= keys match the fleet's
    # routing keys and tenant classes).  Fed by the ROUTER'S OWN exact
    # terminal book — every counted submission feeds its matching
    # objectives with its one terminal outcome, so /slo reconciles
    # against /stats' fleet identity.  Empty = off (byte-identical
    # /metrics).
    slo_objectives: Tuple[str, ...] = ()
    slo_burn_threshold: float = 10.0
    slo_alert_for_s: float = 5.0
    slo_alert_clear_s: float = 60.0
    # Synthetic canary prober (serve/prober.py): > 0 starts a
    # background thread pushing one known-ground-truth synthetic probe
    # through the FULL router→engine path every this-many seconds,
    # round-robin over the fleet's models, under the reserved
    # prober_tenant (auto-registered at the LOWEST priority so probes
    # shed first under overload — and the prober itself DROPS, counted,
    # rather than queue when its previous probe is still in flight).
    # Probe latency/quality/availability export as dsod_probe_*;
    # because probes ride the real door they also feed the router book
    # and any model-scoped SLO — outages fire burn-rate alerts even at
    # zero live traffic.  0 = off.
    prober_interval_s: float = 0.0
    prober_tenant: str = "_probe"
    # Square pixel size of the synthetic probe images (resized into the
    # target model's resolution buckets like any request).
    prober_px: int = 64
    # Per-probe HTTP timeout.
    prober_timeout_s: float = 10.0
    # Router-tier flight recorder (utils/flightrecorder.py; same knob
    # block as serve.flight_recorder).  Samples the ROUTER'S OWN book
    # (tenant/outcome counters, replica up + breaker gauges) — never a
    # per-second scrape of every replica — and triggers an incident
    # bundle on replica transport failures, SLO burn firings, and
    # SIGTERM.  The router /incidents endpoint aggregates its own
    # bundles with every replica's (in-process read direct, remotes
    # scraped bounded).
    flight_recorder: bool = False
    recorder_dir: str = ""
    recorder_sample_s: float = 1.0
    recorder_segment_kb: int = 256
    recorder_keep_segments: int = 16
    recorder_bundle_window_s: float = 300.0
    recorder_debounce_s: float = 30.0

    # -- closed-loop fleet controller (serve/controller.py;
    #    docs/SERVING.md "Fleet control plane") -----------------------
    # False (default): no controller thread, no dsod_ctrl_* families —
    # /metrics stays byte-identical.  True: a sensor-driven control
    # loop heals dead replicas, scales the fleet out on queue-bound SLO
    # burn (and REFUSES, recording why, when the stage-share
    # attribution says the bottleneck is host- or device-side — more
    # replicas on the same device would not help), and scales in with
    # drain-then-retire, never killing in-flight work.
    controller: bool = False
    # Seconds between controller policy evaluations (one tick).
    ctrl_interval_s: float = 5.0
    # Healing/scaling floor per replica set; 0 = the group's configured
    # member count (heal back to what the config promised).
    ctrl_target_replicas: int = 0
    # Scale-out ceiling per replica set (supervised members included).
    ctrl_max_replicas: int = 4
    # Scale-out trigger: SLO burn at or past this rate...
    ctrl_scale_out_burn: float = 2.0
    # ...AND the replicas' queue stage share at or past this fraction
    # (queue-bound — the one bottleneck another replica absorbs).
    ctrl_queue_share: float = 0.5
    # Scale-in trigger: burn at or below this rate while the set holds
    # more members than the target.
    ctrl_scale_in_burn: float = 0.1
    # Hysteresis: a trigger must hold this long before the controller
    # acts (fake-clock-provable, the degraded-ladder dwell idiom)...
    ctrl_dwell_s: float = 10.0
    # ...and after any scale action the policy holds off this long.
    ctrl_cooldown_s: float = 30.0
    # Drain-then-retire grace: a draining replica leaves routing
    # immediately; its process is retired (SIGTERM first — the
    # replica's own clean drain) only after this many seconds.
    ctrl_drain_grace_s: float = 5.0
    # Replica spawn argv template for scale-out/heal, with ``{port}``
    # and ``{port_file}`` placeholders (e.g. the tools/serve.py
    # single-engine command line).  Empty = the controller can
    # drain/retire and refuse, but never spawn.
    ctrl_spawn_cmd: Tuple[str, ...] = ()
    # Seconds a spawned replica gets to bind its port and turn healthy
    # before the supervisor books the attempt as a crash-loop failure.
    ctrl_spawn_deadline_s: float = 150.0
    # Crash-loop backoff between supervised spawn attempts (base,
    # doubled per consecutive failure, capped).
    ctrl_backoff_s: float = 2.0
    ctrl_backoff_max_s: float = 60.0
    # True: arm a PreemptionGuard (utils/observability.py) inside the
    # controller — a SIGTERM-style preemption notice drains supervised
    # replicas instead of letting them die with work in flight, and
    # scale-out is refused while the notice stands.
    ctrl_spot_guard: bool = False

    # -- progressive checkpoint delivery (serve/rollout.py;
    #    docs/SERVING.md "Fleet control plane") -----------------------
    # Non-empty: watch this checkpoint directory and deliver new steps
    # progressively — canary ONE replica, score it, then promote
    # fleet-wide or auto-roll-back and denylist the step — instead of
    # every replica hot-reloading at once.  Empty (default): off,
    # byte-identical /metrics.
    rollout_ckpt_dir: str = ""
    # Replica set the rollout drives (default: the fleet's single
    # model; required when the fleet serves several).
    rollout_model: str = ""
    # Seconds between checkpoint-directory polls / state-machine ticks.
    rollout_poll_s: float = 5.0
    # Seconds the canary bakes on live + probe traffic before the
    # verdict is taken.
    rollout_bake_s: float = 10.0
    # Ground-truth canary probes per verdict (serve/prober.py probe
    # set), sent DIRECTLY to the canary replica and to a stable
    # baseline replica for the relative comparison.
    rollout_probes: int = 6
    rollout_probe_px: int = 64
    # Verdict fails when canary probe MAE exceeds the baseline
    # replica's by more than this...
    rollout_mae_degrade: float = 0.1
    # ...or exceeds this absolute ceiling (0 = no absolute ceiling)...
    rollout_mae_max: float = 0.0
    # ...or the canary's drift PSI (serve/quality.py, when the quality
    # monitors are armed) exceeds this (0 = PSI not consulted)...
    rollout_psi_max: float = 0.0
    # ...or fewer than this fraction of canary probes answered.
    rollout_min_avail: float = 1.0

    # -- router-door response cache (serve/cache.py; docs/SERVING.md
    #    "Router cache") ----------------------------------------------
    # Byte budget for the content-addressed response LRU (entries are
    # keyed on payload hash × model × precision arm × loaded
    # checkpoint step).  0 (default): cache fully off — no object, no
    # threads, byte-identical /metrics.
    cache_bytes: int = 0
    # Fold concurrent identical payloads into ONE engine submit with N
    # responses (each booked cache_hit).  Only meaningful with
    # cache_bytes > 0.
    cache_coalesce: bool = True
    # Arm the perceptual-hash near-dup arm: resize-normalized hits for
    # perceptually identical payloads.  Quality-gated offline by
    # tools/cache_gate.py; arm the online shadow gate via
    # cache_shadow_sample.
    cache_near_dup: bool = False
    # Near-dup match budget in Hamming bits over the 256-bit phash
    # (0 = exact-phash matches only; ~16 tolerates typical re-encode/
    # resize perturbations — see tools/cache_baseline.json).
    cache_near_dup_hamming: int = 0
    # Shadow-score every Nth near-dup hit against a fresh engine
    # forward, off the request path (0 = no shadow scoring).
    cache_shadow_sample: int = 0

    # -- streaming-video sessions (serve/streams.py; docs/SERVING.md
    #    "Streaming") ----------------------------------------------------
    # Maximum concurrent per-client stream sessions (the X-Stream-ID
    # header opens one).  0 (default): streaming fully off — no session
    # table, no dsod_stream_* families, byte-identical /metrics, and
    # the batcher never sees a stream key.  A NEW stream past the cap
    # sheds loudly at the door (429 kind=stream_budget) — existing
    # sessions are never silently evicted to make room.
    stream_sessions: int = 0
    # Idle TTL: a session untouched this long is evicted (LRU order)
    # and counted into dsod_stream_expired_total.
    stream_ttl_s: float = 30.0
    # Temporal-coherence fast path: when a frame's 256-bit phash is
    # within this many Hamming bits of the stream's previous frame,
    # serve the previous mask WITHOUT a forward (terminal class
    # `stream_reuse`).  0 = fast path off (sessions still track state
    # and pin replicas).  Quality-gated offline by tools/stream_gate.py
    # (checked-in tools/stream_baseline.json) and online by the cache
    # shadow monitors.
    stream_reuse_hamming: int = 0
    # EMA mask blend for flicker damping: on a FULL forward for a
    # stream that has a previous mask of the same shape, the response
    # becomes blend*prev + (1-blend)*new.  0 (default) = off — full
    # forwards are bitwise the engine's own answer.
    stream_ema_blend: float = 0.0


def fleet_config_from_dict(d: Dict) -> FleetConfig:
    """Build + validate a FleetConfig from its JSON dict (the
    ``tools/serve.py --fleet-config`` file format).  Loud ValueError on
    an unknown key, a duplicate model/tenant name, or a member without
    exactly one backend source."""
    d = dict(d)
    models = []
    for md in d.pop("models", []):
        md = dict(md)
        unknown = set(md) - {f.name for f in
                             dataclasses.fields(FleetModelConfig)}
        if unknown:
            raise ValueError(
                f"unknown fleet model key(s) {sorted(unknown)} in {md!r}")
        if "overrides" in md:
            md["overrides"] = tuple(md["overrides"])
        if "urls" in md:
            md["urls"] = tuple(md["urls"])
        models.append(FleetModelConfig(**md))
    tenants = []
    for td in d.pop("tenants", []):
        td = dict(td)
        unknown = set(td) - {f.name for f in
                             dataclasses.fields(FleetTenantConfig)}
        if unknown:
            raise ValueError(
                f"unknown fleet tenant key(s) {sorted(unknown)} in {td!r}")
        tenants.append(FleetTenantConfig(**td))
    known = {f.name for f in dataclasses.fields(FleetConfig)} \
        - {"models", "tenants"}
    unknown = set(d) - known
    if unknown:
        raise ValueError(f"unknown fleet config key(s) {sorted(unknown)}")
    if "ctrl_spawn_cmd" in d:
        d["ctrl_spawn_cmd"] = tuple(d["ctrl_spawn_cmd"])
    fc = FleetConfig(models=tuple(models), tenants=tuple(tenants), **d)
    return validate_fleet_config(fc)


def validate_fleet_config(fc: FleetConfig) -> FleetConfig:
    """Invariants a fleet must satisfy before a single engine warms:
    at least one model, unique routing keys, exactly one backend source
    per member, unique tenant names.  Returns ``fc`` (with the default
    tenant auto-registered when missing)."""
    if not fc.models:
        raise ValueError("fleet config needs at least one model")
    seen = set()
    for m in fc.models:
        if not m.name:
            raise ValueError(f"fleet model {m!r} needs a name (routing key)")
        if m.name in seen:
            raise ValueError(f"duplicate fleet model name {m.name!r}")
        seen.add(m.name)
        if m.url and (m.config or m.ckpt_dir or m.overrides):
            raise ValueError(
                f"fleet model {m.name!r}: url is exclusive of "
                "config/ckpt_dir/overrides (the remote process owns its "
                "own config)")
        if m.urls and (m.url or m.config or m.ckpt_dir or m.overrides):
            raise ValueError(
                f"fleet model {m.name!r}: urls (replica set) is "
                "exclusive of url/config/ckpt_dir/overrides (each "
                "remote replica owns its own config)")
        if m.urls and len(set(m.urls)) != len(m.urls):
            raise ValueError(
                f"fleet model {m.name!r}: duplicate replica url in "
                f"{m.urls}")
        if not m.url and not m.urls and not m.ckpt_dir and not m.config:
            raise ValueError(
                f"fleet model {m.name!r} needs one of config / ckpt_dir "
                "/ url / urls")
    tseen = set()
    for t in fc.tenants:
        if not t.name:
            raise ValueError(f"fleet tenant {t!r} needs a name")
        if t.name in tseen:
            raise ValueError(f"duplicate fleet tenant name {t.name!r}")
        tseen.add(t.name)
        if t.rate_rps < 0 or t.burst < 0:
            raise ValueError(
                f"fleet tenant {t.name!r}: rate_rps/burst must be >= 0")
    if fc.retry_max_attempts < 1:
        raise ValueError(
            f"fleet retry_max_attempts must be >= 1 (1 = no retry), "
            f"got {fc.retry_max_attempts}")
    if fc.retry_backoff_ms < 0 or fc.retry_backoff_max_ms < 0:
        raise ValueError(
            "fleet retry_backoff_ms/retry_backoff_max_ms must be >= 0")
    if fc.hedge_ms < 0 and fc.hedge_ms != -1:
        raise ValueError(
            f"fleet hedge_ms must be >= 0 (0 = off) or exactly -1 "
            f"(auto: hedge at observed p95), got {fc.hedge_ms}")
    if fc.breaker_failures < 1:
        raise ValueError(
            f"fleet breaker_failures must be >= 1, got "
            f"{fc.breaker_failures}")
    if fc.breaker_reset_s <= 0:
        raise ValueError(
            f"fleet breaker_reset_s must be > 0, got {fc.breaker_reset_s}")
    if not 0.0 <= fc.trace_sample <= 1.0:
        raise ValueError(
            f"fleet trace_sample must be in [0, 1], got {fc.trace_sample}")
    if fc.trace_capacity < 1 or fc.trace_worst_n < 0:
        raise ValueError(
            "fleet trace_capacity must be >= 1 and trace_worst_n >= 0, "
            f"got {fc.trace_capacity}/{fc.trace_worst_n}")
    if fc.slo_objectives:
        # Loud parse at config time, not first scrape (utils/slo.py).
        from ..utils.slo import parse_slos

        parse_slos(fc.slo_objectives)
    if fc.slo_burn_threshold <= 0:
        raise ValueError(
            f"fleet slo_burn_threshold must be > 0, got "
            f"{fc.slo_burn_threshold}")
    if fc.slo_alert_for_s < 0 or fc.slo_alert_clear_s < 0:
        raise ValueError(
            "fleet slo_alert_for_s/slo_alert_clear_s must be >= 0")
    if fc.prober_interval_s < 0:
        raise ValueError(
            f"fleet prober_interval_s must be >= 0 (0 = off), got "
            f"{fc.prober_interval_s}")
    if fc.prober_interval_s > 0:
        if not fc.prober_tenant:
            raise ValueError(
                "fleet prober_tenant must be non-empty when the prober "
                "is on")
        if fc.prober_px < 8:
            raise ValueError(
                f"fleet prober_px must be >= 8, got {fc.prober_px}")
        if fc.prober_timeout_s <= 0:
            raise ValueError(
                f"fleet prober_timeout_s must be > 0, got "
                f"{fc.prober_timeout_s}")
    if fc.flight_recorder:
        # Loud at config time, not first sample (the recorder knobs
        # are re-validated by FlightRecorder itself; the dir check is
        # the one only the config layer can make early).
        if not fc.recorder_dir:
            raise ValueError(
                "fleet flight_recorder=true needs recorder_dir (the "
                "on-disk segment-ring location)")
        if fc.recorder_sample_s <= 0:
            raise ValueError(
                f"fleet recorder_sample_s must be > 0, got "
                f"{fc.recorder_sample_s}")
    if fc.controller:
        if fc.ctrl_interval_s <= 0:
            raise ValueError(
                f"fleet ctrl_interval_s must be > 0, got "
                f"{fc.ctrl_interval_s}")
        if fc.ctrl_target_replicas < 0:
            raise ValueError(
                f"fleet ctrl_target_replicas must be >= 0 (0 = the "
                f"group's configured size), got {fc.ctrl_target_replicas}")
        if fc.ctrl_max_replicas < 1:
            raise ValueError(
                f"fleet ctrl_max_replicas must be >= 1, got "
                f"{fc.ctrl_max_replicas}")
        if fc.ctrl_scale_out_burn <= 0 or fc.ctrl_scale_in_burn < 0:
            raise ValueError(
                "fleet ctrl_scale_out_burn must be > 0 and "
                "ctrl_scale_in_burn >= 0, got "
                f"{fc.ctrl_scale_out_burn}/{fc.ctrl_scale_in_burn}")
        if not 0.0 <= fc.ctrl_queue_share <= 1.0:
            raise ValueError(
                f"fleet ctrl_queue_share must be in [0, 1], got "
                f"{fc.ctrl_queue_share}")
        if fc.ctrl_dwell_s < 0 or fc.ctrl_cooldown_s < 0 \
                or fc.ctrl_drain_grace_s < 0:
            raise ValueError(
                "fleet ctrl_dwell_s/ctrl_cooldown_s/ctrl_drain_grace_s "
                "must be >= 0")
        if fc.ctrl_spawn_cmd:
            joined = " ".join(fc.ctrl_spawn_cmd)
            if "{port}" not in joined or "{port_file}" not in joined:
                raise ValueError(
                    "fleet ctrl_spawn_cmd must contain both {port} and "
                    "{port_file} placeholders (the supervisor needs to "
                    "assign the port and learn when the replica bound "
                    "it) — got " + repr(fc.ctrl_spawn_cmd))
        if fc.ctrl_spawn_deadline_s <= 0 or fc.ctrl_backoff_s <= 0 \
                or fc.ctrl_backoff_max_s < fc.ctrl_backoff_s:
            raise ValueError(
                "fleet ctrl_spawn_deadline_s/ctrl_backoff_s must be > 0 "
                "and ctrl_backoff_max_s >= ctrl_backoff_s, got "
                f"{fc.ctrl_spawn_deadline_s}/{fc.ctrl_backoff_s}/"
                f"{fc.ctrl_backoff_max_s}")
    if fc.rollout_ckpt_dir:
        if fc.rollout_model:
            if fc.rollout_model not in seen:
                raise ValueError(
                    f"fleet rollout_model {fc.rollout_model!r} is not a "
                    f"configured model (have {sorted(seen)})")
        elif len(fc.models) != 1:
            raise ValueError(
                "fleet rollout_model is required when the fleet serves "
                "more than one model (the rollout drives ONE replica "
                "set)")
        if fc.rollout_poll_s <= 0 or fc.rollout_bake_s < 0:
            raise ValueError(
                "fleet rollout_poll_s must be > 0 and rollout_bake_s "
                f">= 0, got {fc.rollout_poll_s}/{fc.rollout_bake_s}")
        if fc.rollout_probes < 1 or fc.rollout_probe_px < 8:
            raise ValueError(
                "fleet rollout_probes must be >= 1 and rollout_probe_px "
                f">= 8, got {fc.rollout_probes}/{fc.rollout_probe_px}")
        if fc.rollout_mae_degrade < 0 or fc.rollout_mae_max < 0 \
                or fc.rollout_psi_max < 0:
            raise ValueError(
                "fleet rollout_mae_degrade/rollout_mae_max/"
                "rollout_psi_max must be >= 0")
        if not 0.0 <= fc.rollout_min_avail <= 1.0:
            raise ValueError(
                f"fleet rollout_min_avail must be in [0, 1], got "
                f"{fc.rollout_min_avail}")
    if fc.cache_bytes < 0:
        raise ValueError(
            f"fleet cache_bytes must be >= 0 (0 = off), got "
            f"{fc.cache_bytes}")
    if fc.cache_near_dup and fc.cache_bytes <= 0:
        raise ValueError(
            "fleet cache_near_dup requires cache_bytes > 0 — the "
            "near-dup arm serves out of the exact arm's LRU")
    if fc.cache_near_dup_hamming < 0 \
            or fc.cache_near_dup_hamming > 256:
        raise ValueError(
            "fleet cache_near_dup_hamming must be in [0, 256] (bits "
            f"over the 256-bit phash), got {fc.cache_near_dup_hamming}")
    if fc.cache_near_dup_hamming > 0 and not fc.cache_near_dup:
        raise ValueError(
            "fleet cache_near_dup_hamming is set but cache_near_dup is "
            "off — a Hamming budget without the near-dup arm does "
            "nothing (loud beats silent)")
    if fc.cache_shadow_sample < 0:
        raise ValueError(
            f"fleet cache_shadow_sample must be >= 0 (every Nth "
            f"near-dup hit; 0 = off), got {fc.cache_shadow_sample}")
    if fc.cache_shadow_sample > 0 and not fc.cache_near_dup:
        raise ValueError(
            "fleet cache_shadow_sample is set but cache_near_dup is "
            "off — only near-dup hits are shadow-scored (exact hits "
            "are bitwise the engine's own answer)")
    if fc.stream_sessions < 0:
        raise ValueError(
            f"fleet stream_sessions must be >= 0 (0 = streaming off), "
            f"got {fc.stream_sessions}")
    if fc.stream_sessions > 0 and fc.stream_ttl_s <= 0:
        raise ValueError(
            f"fleet stream_ttl_s must be > 0 when streaming is on, got "
            f"{fc.stream_ttl_s}")
    if fc.stream_reuse_hamming < 0 or fc.stream_reuse_hamming > 256:
        raise ValueError(
            "fleet stream_reuse_hamming must be in [0, 256] (bits over "
            f"the 256-bit phash), got {fc.stream_reuse_hamming}")
    if fc.stream_reuse_hamming > 0 and fc.stream_sessions <= 0:
        raise ValueError(
            "fleet stream_reuse_hamming is set but stream_sessions is "
            "0 — the temporal-coherence fast path serves out of a "
            "stream session (loud beats silent)")
    if not 0.0 <= fc.stream_ema_blend < 1.0:
        raise ValueError(
            f"fleet stream_ema_blend must be in [0, 1), got "
            f"{fc.stream_ema_blend}")
    if fc.stream_ema_blend > 0 and fc.stream_sessions <= 0:
        raise ValueError(
            "fleet stream_ema_blend is set but stream_sessions is 0 — "
            "the blend reads a stream session's previous mask (loud "
            "beats silent)")
    if fc.default_tenant not in tseen:
        low = min((t.priority for t in fc.tenants), default=0)
        fc = dataclasses.replace(
            fc, tenants=fc.tenants + (FleetTenantConfig(
                name=fc.default_tenant, priority=low),))
        tseen.add(fc.default_tenant)
    if fc.prober_interval_s > 0 and fc.prober_tenant not in tseen:
        # Reserved probe tenant, registered AFTER the default tenant so
        # it lands STRICTLY below every class (default included): under
        # overload probes are the FIRST thing the router sheds —
        # synthetic traffic must never displace a real request.
        low = min(t.priority for t in fc.tenants) - 1
        fc = dataclasses.replace(
            fc, tenants=fc.tenants + (FleetTenantConfig(
                name=fc.prober_tenant, priority=low),))
    return fc


@dataclasses.dataclass(frozen=True)
class ExperimentConfig:
    name: str = "default"
    data: DataConfig = dataclasses.field(default_factory=DataConfig)
    model: ModelConfig = dataclasses.field(default_factory=ModelConfig)
    loss: LossConfig = dataclasses.field(default_factory=LossConfig)
    optim: OptimConfig = dataclasses.field(default_factory=OptimConfig)
    mesh: MeshConfig = dataclasses.field(default_factory=MeshConfig)
    parallel: ParallelConfig = dataclasses.field(
        default_factory=ParallelConfig)
    serve: ServeConfig = dataclasses.field(default_factory=ServeConfig)
    global_batch_size: int = 8
    num_epochs: int = 50
    steps_per_epoch: Optional[int] = None  # None → derived from dataset size
    seed: int = 0
    # Device-side step chunking (docs/PERFORMANCE.md): fold this many
    # train steps into ONE compiled dispatch (a lax.scan over stacked
    # batches inside the step program).  Amortises the per-step host
    # tax — Python loop, dispatch latency, fault-plan checks, metric
    # readback — over k steps; the loop then observes the run only at
    # chunk boundaries, so every cadence knob (log/eval/checkpoint/
    # stop-polling) must be divisible by k (validate_steps_per_dispatch
    # raises otherwise).  1 = the historical per-step path, unchanged.
    # DSOD_FAULTS forces 1 (per-step poison/stall/SIGTERM semantics).
    steps_per_dispatch: int = 1
    log_every_steps: int = 20
    checkpoint_every_steps: int = 500
    checkpoint_dir: str = "checkpoints"
    keep_checkpoints: int = 3
    eval_every_steps: int = 0  # 0 = no in-training eval
    best_metric: Optional[str] = None  # e.g. "max_fbeta": keep best ckpts
    best_mode: str = "max"  # "min" for lower-is-better metrics (mae)
    tensorboard: bool = True  # event files under <workdir>/tb
    # >0: arm the step watchdog (resilience/watchdog.py): a train step
    # exceeding this many seconds means the wedged-dispatch failure
    # mode (device answers enumeration, programs never complete) —
    # dump stacks + last metrics and exit with code 114 so the
    # supervising layer re-fires and resumes.  Must exceed the slowest
    # legitimate step.  0 = off.
    watchdog_deadline_s: float = 0.0
    # Grace for the FIRST step, which includes XLA compilation
    # (minutes, legitimately).  Only read when the watchdog is armed.
    watchdog_compile_grace_s: float = 600.0
    # Opt-in trainer telemetry sidecar (utils/telemetry.py;
    # docs/OBSERVABILITY.md): >= 0 binds a stdlib HTTP server on that
    # port (0 = ephemeral; publish via train.py --telemetry-port-file)
    # exposing /metrics (PipelineStats + StepTimer + device memory),
    # /healthz (step-watchdog heartbeat), /debug/traces, and
    # /debug/profile?seconds=N (on-demand jax.profiler window).
    # -1 (default) = off: zero threads, zero sockets.
    telemetry_port: int = -1
    # Fraction of train chunks whose span timelines are recorded
    # (data-wait/dispatch/flush + ckpt/eval spans correlated to step
    # numbers — utils/tracing.py).  0 = off (no per-chunk clock reads).
    trace_sample: float = 0.0
    # -- training numerics telemetry (utils/modelhealth.py;
    #    docs/OBSERVABILITY.md "Model health").  OFF by default: the
    #    compiled step and the metric stream are byte-for-byte the
    #    historical ones.  On, every step additionally emits per-
    #    parameter-group gradient norms, non-finite PROVENANCE (which
    #    group first went NaN — skip_nonfinite counts but cannot
    #    attribute), and the update/weight ratio; the host aggregates
    #    them into dsod_health_* sidecar families and feeds the alert
    #    engine (utils/alerts.py, /alerts on the sidecar).
    health_numerics: bool = False
    # Extra alert rules (colon DSL, see serve.alert_rules) joining the
    # built-in numerics set (nonfinite / grad-norm-z / loss-z).
    health_alert_rules: Tuple[str, ...] = ()
    # Clear dwell of the built-in numerics rules: how long the signal
    # must stay healthy before an alert resolves (hysteresis).
    health_alert_clear_s: float = 30.0
    # Opt-in hand-off to the PR-1 resilience supervisor: when a
    # rollback-hinted alert (numerics_nonfinite) FIRES, fit() raises
    # the divergence RuntimeError the supervisor's rollback-and-retry
    # policy recognizes — the alert engine becomes a rollback hint,
    # not just a dashboard.  Off: alerts only report.
    health_rollback_hint: bool = False
    # -- capacity & SLO observability, trainer side (utils/capacity.py,
    #    utils/slo.py; docs/OBSERVABILITY.md "Capacity & SLO").  Both
    #    OFF by default: the step program, the metric stream, and the
    #    sidecar /metrics are byte-for-byte the historical ones.
    # Live train-step cost ledger: each step program is additionally
    # AOT-compiled ONCE for its cost_analysis()/memory_analysis()
    # (one extra compile per static shape, paid only when opted in)
    # and the StepTimer's measured step time turns it into live
    # MFU/roofline gauges on the telemetry sidecar.
    capacity_ledger: bool = False
    # Goodput SLO on train steps (same colon DSL as
    # serve.slo_objectives; kind=latency over per-step wall time is
    # the meaningful form — every completed step feeds one event):
    # e.g. "goodput:all:latency:0.99:600:2000" = 99% of steps under
    # 2 s over any 10-minute window.  Surfaces as dsod_slo_* + /slo on
    # the sidecar; burn/budget alerts degrade the sidecar /healthz.
    slo_objectives: Tuple[str, ...] = ()
    slo_burn_threshold: float = 10.0
    slo_alert_for_s: float = 5.0
    slo_alert_clear_s: float = 60.0
    # -- black-box flight recorder, trainer side
    #    (utils/flightrecorder.py; docs/OBSERVABILITY.md "Flight
    #    recorder & incidents").  OFF by default: no thread, no files,
    #    the loop and sidecar surface byte-identical.  On, the trainer
    #    telemetry registry (built even when the sidecar port is off)
    #    is sampled into an on-disk segment ring under recorder_dir
    #    (default <workdir>/flightrec), checkpoint/eval/preemption/
    #    rollback events are recorded, and watchdog trips / health-
    #    alert firings / train crashes snapshot incident bundles —
    #    evidence that survives the exit-114 the watchdog's stall
    #    policy mandates.  resilience/supervisor.py notes each
    #    rollback into the same ring between attempts.
    flight_recorder: bool = False
    recorder_dir: str = ""
    recorder_sample_s: float = 1.0
    recorder_segment_kb: int = 256
    recorder_keep_segments: int = 16
    recorder_bundle_window_s: float = 300.0
    recorder_debounce_s: float = 30.0

    def replace(self, **kw) -> "ExperimentConfig":
        return dataclasses.replace(self, **kw)


def validate_steps_per_dispatch(cfg: ExperimentConfig,
                                loader_steps_per_epoch: Optional[int] = None,
                                ) -> None:
    """Chunk-boundary divisibility contract for ``steps_per_dispatch``.

    With k steps folded into one dispatch the train loop only observes
    the run at chunk boundaries, so every step-cadence knob must be a
    multiple of k or its events would fall mid-chunk and silently never
    fire.  Raises ``ValueError`` naming the offending (knob, value)
    pair.  ``loader_steps_per_epoch`` lets ``fit()`` also check the
    loader's actual epoch period (a partial trailing chunk per epoch
    would drop steps and skew the epoch accounting).
    """
    k = cfg.steps_per_dispatch
    if k < 1:
        raise ValueError(
            f"steps_per_dispatch must be >= 1, got {k}")
    if k == 1:
        return
    pairs = [
        ("log_every_steps", cfg.log_every_steps),
        ("eval_every_steps", cfg.eval_every_steps),
        ("checkpoint_every_steps", cfg.checkpoint_every_steps),
        ("steps_per_epoch", cfg.steps_per_epoch or 0),
        ("loader steps_per_epoch", loader_steps_per_epoch or 0),
    ]
    for name, value in pairs:
        if value and value % k:
            raise ValueError(
                f"steps_per_dispatch={k} does not divide {name}={value}"
                " — the chunked loop only observes chunk boundaries, so"
                f" a {name} event would fall mid-chunk and never fire."
                f"  Pick k dividing every cadence knob or change {name}"
                " to a multiple of k (docs/PERFORMANCE.md"
                " \"Device-side step chunking\")")


def validate_parallel(cfg: ExperimentConfig) -> None:
    """Loud validation of the sharding-engine knobs (ParallelConfig)."""
    par = cfg.parallel
    if par.engine == "legacy":
        raise ValueError(
            "parallel.engine=legacy: the legacy step builders were "
            "removed in round 18 after the rules engine shipped "
            "bitwise-proven — parallel.engine=rules is the only engine")
    if par.engine != "rules":
        raise ValueError(
            f"parallel.engine must be rules, got {par.engine!r}")
    if par.preset not in ("auto", "dp", "tp", "sp", "fsdp"):
        raise ValueError(
            "parallel.preset must be auto|dp|tp|sp|fsdp, got "
            f"{par.preset!r}")
    if par.zero not in (0, 1, 2):
        raise ValueError(f"parallel.zero must be 0|1|2, got {par.zero!r}")
    if par.grad_compression not in ("none", "bf16", "int8_ef"):
        raise ValueError(
            "parallel.grad_compression must be none|bf16|int8_ef, got "
            f"{par.grad_compression!r}")
    if par.comm_bucket_mb < 0:
        raise ValueError(
            f"parallel.comm_bucket_mb must be >= 0, got "
            f"{par.comm_bucket_mb}")
    if cfg.mesh.data_hosts < 1:
        raise ValueError(
            f"mesh.data_hosts must be >= 1, got {cfg.mesh.data_hosts}"
            " (divisibility vs the resolved data axis is checked at "
            "mesh build time — the axis may be -1 here)")
    if par.zero and cfg.optim.zero1:
        raise ValueError(
            "optim.zero1 and parallel.zero are both set — pick ONE "
            "spelling (parallel.zero on the rules engine)")
    if par.zero and cfg.model.sync_bn:
        raise ValueError(
            "parallel.zero routes through the GSPMD preset, which has "
            "no named mesh axis: set model.sync_bn=false (BN stats are "
            "global-batch there, strictly stronger)")
    if par.preset == "fsdp":
        if cfg.model.sync_bn:
            raise ValueError(
                "parallel.preset=fsdp routes through the GSPMD path, "
                "which has no named mesh axis: set model.sync_bn=false "
                "(BN stats are global-batch there, strictly stronger)")
        if cfg.mesh.model != 1 or cfg.mesh.seq != 1:
            raise ValueError(
                "parallel.preset=fsdp shards params over the data axis "
                "only — set mesh.model=1 and mesh.seq=1 (got model="
                f"{cfg.mesh.model}, seq={cfg.mesh.seq})")


_REGISTRY: Dict[str, Callable[[], ExperimentConfig]] = {}


def register_config(name: str):
    """Decorator: register a zero-arg factory under ``name``."""

    def deco(fn: Callable[[], ExperimentConfig]):
        if name in _REGISTRY:
            raise KeyError(f"config {name!r} already registered")
        _REGISTRY[name] = fn
        return fn

    return deco


def get_config(name: str, **overrides) -> ExperimentConfig:
    if name not in _REGISTRY:
        raise KeyError(f"unknown config {name!r}; known: {sorted(_REGISTRY)}")
    cfg = _REGISTRY[name]()
    if overrides:
        cfg = dataclasses.replace(cfg, **overrides)
    return cfg


def list_configs():
    return sorted(_REGISTRY)


def _coerce(value: str, ftype):
    """Parse a CLI string into a dataclass field's annotated type.

    Typed by the annotation, not the current value, so fields defaulting
    to ``None`` (``Optional[int] steps_per_epoch``) still coerce.
    """
    import typing

    origin = typing.get_origin(ftype)
    if origin is typing.Union:  # Optional[X] and friends
        args = [a for a in typing.get_args(ftype) if a is not type(None)]
        if value.lower() in ("none", "null"):
            return None
        return _coerce(value, args[0])
    if origin is tuple:
        parts = [p for p in value.replace("(", "").replace(")", "").split(",") if p]
        args = typing.get_args(ftype)
        elem = args[0] if args else str
        return tuple(_coerce(p, elem) for p in parts)
    if ftype is bool:
        if value.lower() in ("1", "true", "yes"):
            return True
        if value.lower() in ("0", "false", "no"):
            return False
        raise ValueError(f"expected bool, got {value!r}")
    if ftype is int:
        return int(value)
    if ftype is float:
        return float(value)
    if ftype is str:
        return value
    raise ValueError(f"cannot coerce {value!r} onto {ftype!r}")


def config_from_dict(d: Dict) -> ExperimentConfig:
    """Rebuild an ExperimentConfig from its JSON dict (the checkpoint
    config sidecar, ckpt/manager.py) — checkpoints are self-describing,
    so ``test.py`` can run without naming the config again."""
    import typing

    def build(cls, dd):
        hints = typing.get_type_hints(cls)
        kwargs = {}
        for f in dataclasses.fields(cls):
            if f.name not in dd:
                continue
            v = dd[f.name]
            ft = hints[f.name]
            if dataclasses.is_dataclass(ft) and isinstance(v, dict):
                kwargs[f.name] = build(ft, v)
            elif typing.get_origin(ft) is tuple and isinstance(v, list):
                kwargs[f.name] = tuple(v)
            else:
                kwargs[f.name] = v
        return cls(**kwargs)

    return build(ExperimentConfig, d)


def apply_overrides(cfg: ExperimentConfig, overrides) -> ExperimentConfig:
    """Apply ``section.field=value`` CLI overrides (SURVEY.md §2 C13).

    Dotted paths address nested config dataclasses:
    ``data.image_size=64,64 optim.lr=0.01 model.name=u2net``.
    Top-level fields work without a dot (``global_batch_size=16``).
    """
    for ov in overrides or []:
        if "=" not in ov:
            raise ValueError(f"override {ov!r} is not key=value")
        path, value = ov.split("=", 1)
        keys = path.strip().split(".")
        # Walk down, collecting the chain of dataclass instances.
        objs = [cfg]
        for k in keys[:-1]:
            if not hasattr(objs[-1], k) or not dataclasses.is_dataclass(
                    getattr(objs[-1], k)):
                raise KeyError(f"no config field {'.'.join(keys)!r}")
            objs.append(getattr(objs[-1], k))
        leaf = keys[-1]
        fields = {f.name: f for f in dataclasses.fields(type(objs[-1]))}
        if leaf not in fields:
            raise KeyError(f"no config field {'.'.join(keys)!r}")
        ftype = fields[leaf].type
        if isinstance(ftype, str):  # `from __future__ import annotations`
            import typing

            ftype = typing.get_type_hints(type(objs[-1]))[leaf]
        new = _coerce(value.strip(), ftype)
        # Rebuild the frozen chain bottom-up.
        for obj, key in zip(reversed(objs), reversed(keys)):
            new = dataclasses.replace(obj, **{key: new})
        cfg = new
    return cfg
