"""The five driver configs from BASELINE.json:7-11 (see SURVEY.md §2).

1. MINet-VGG16, DUTS-TR 320×320, batch=1 single-image forward (CPU ref)
2. MINet-ResNet50, DUTS-TR full data-parallel train
3. HDFNet RGB-D (NJU2K / NLPR) — two-stream depth-fusion encoder
4. U²-Net / BASNet — nested U-decoder + 7-level deep supervision
5. Swin-T backbone SOD (stretch — transformer encoder on TPU)
"""

from .base import (
    DataConfig,
    ExperimentConfig,
    LossConfig,
    MeshConfig,
    ModelConfig,
    OptimConfig,
    register_config,
)


@register_config("minet_vgg16_ref")
def minet_vgg16_ref() -> ExperimentConfig:
    """Config 1: MINet-VGG16 single-image forward reference."""
    return ExperimentConfig(
        name="minet_vgg16_ref",
        data=DataConfig(dataset="synthetic", image_size=(320, 320)),
        model=ModelConfig(name="minet", backbone="vgg16", sync_bn=False),
        loss=LossConfig(cel=1.0),
        optim=OptimConfig(lr=0.001),
        global_batch_size=1,
        mesh=MeshConfig(data=1),
    )


@register_config("minet_r50_dp")
def minet_r50_dp() -> ExperimentConfig:
    """Config 2: MINet-ResNet50 full data-parallel training (flagship)."""
    return ExperimentConfig(
        name="minet_r50_dp",
        # rotate_degrees=10: the MINet-era joint-transform recipe
        # (hflip + small random rotation) on the host data plane.
        data=DataConfig(dataset="duts", image_size=(320, 320),
                        rotate_degrees=10.0),
        model=ModelConfig(name="minet", backbone="resnet50", sync_bn=True),
        loss=LossConfig(cel=1.0),
        optim=OptimConfig(lr=0.005, schedule="poly"),
        global_batch_size=32,
        num_epochs=50,
    )


@register_config("hdfnet_rgbd")
def hdfnet_rgbd() -> ExperimentConfig:
    """Config 3: HDFNet two-stream RGB-D on NJU2K/NLPR."""
    return ExperimentConfig(
        name="hdfnet_rgbd",
        data=DataConfig(dataset="nju2k", image_size=(320, 320), use_depth=True),
        model=ModelConfig(name="hdfnet", backbone="vgg16", sync_bn=True),
        loss=LossConfig(),
        optim=OptimConfig(lr=0.005),
        global_batch_size=16,
        num_epochs=40,
    )


@register_config("u2net_ds")
def u2net_ds() -> ExperimentConfig:
    """Config 4a: U²-Net — nested U decoder, 7-level deep supervision."""
    return ExperimentConfig(
        name="u2net_ds",
        data=DataConfig(dataset="duts", image_size=(320, 320)),
        model=ModelConfig(name="u2net", backbone="none", sync_bn=True),
        # fused_kernel: same 8-ish-output deep-supervision shape the
        # +7.4% v5e win was measured on (basnet_ds, BASELINE.md).
        loss=LossConfig(bce=1.0, iou=0.0, ssim=0.0, deep_supervision=True,
                        fused_kernel=True),
        optim=OptimConfig(optimizer="adamw", lr=1e-3, weight_decay=0.0),
        global_batch_size=16,
        num_epochs=100,
    )


@register_config("basnet_ds")
def basnet_ds() -> ExperimentConfig:
    """Config 4b: BASNet — predict+refine, BCE+SSIM+IoU hybrid loss."""
    return ExperimentConfig(
        name="basnet_ds",
        data=DataConfig(dataset="duts", image_size=(320, 320)),
        model=ModelConfig(name="basnet", backbone="resnet34", sync_bn=True),
        # fused_kernel: measured +7.4% img/s on v5e for exactly this
        # config (BASELINE.md round-2 TPU session; exactness vs the
        # unfused path is asserted in tests/test_pallas_loss.py).
        loss=LossConfig(bce=1.0, iou=1.0, ssim=1.0, deep_supervision=True,
                        fused_kernel=True),
        optim=OptimConfig(optimizer="adamw", lr=1e-3, weight_decay=0.0),
        global_batch_size=16,
        num_epochs=100,
    )


@register_config("swin_sod")
def swin_sod() -> ExperimentConfig:
    """Config 5 (stretch): Swin-T transformer encoder SOD."""
    return ExperimentConfig(
        name="swin_sod",
        data=DataConfig(dataset="duts", image_size=(320, 320)),
        model=ModelConfig(name="swin_sod", backbone="swin_t", sync_bn=False),
        loss=LossConfig(),
        optim=OptimConfig(optimizer="adamw", lr=3e-4, weight_decay=0.01,
                          warmup_steps=500),
        global_batch_size=16,
        mesh=MeshConfig(data=-1, model=1, seq=1),
    )


@register_config("vit_sod_hires")
def vit_sod_hires() -> ExperimentConfig:
    """Long-context flagship recipe: ViT-SOD at 1024px (4096 global
    tokens).  Image rows shard over ``mesh.seq`` (ring attention;
    ``--set mesh.sp_strategy=ulysses`` for the all-to-all variant when
    heads divide).  Attention defaults to ``attn_impl="xla"``: at every
    operating point measured on v5e (round 2, N=1024) the Pallas flash
    kernel was 2.2x SLOWER than XLA's materialized attention whenever
    the N² scores fit in HBM, and the pre-committed decision rule says
    flash must measurably win to be a default (docs/PERFORMANCE.md).
    ``--set model.attn_impl=flash`` remains the documented memory
    lever — at b16/N=4096 it runs where XLA OOMs — and the round-4
    block sweep (tools/tpu_agenda_r4.sh leg 6) re-flips this default
    if any block shape beats XLA at this config's operating point."""
    return ExperimentConfig(
        name="vit_sod_hires",
        data=DataConfig(dataset="duts", image_size=(1024, 1024)),
        model=ModelConfig(name="vit_sod", backbone="small", sync_bn=False,
                          attn_impl="xla", remat=True),
        loss=LossConfig(bce=1.0, iou=1.0, ssim=1.0),
        optim=OptimConfig(optimizer="adamw", lr=3e-4, weight_decay=0.01,
                          warmup_steps=500),
        global_batch_size=8,
        mesh=MeshConfig(data=1, model=1, seq=-1),
    )


@register_config("gatenet_vgg16")
def gatenet_vgg16() -> ExperimentConfig:
    """Zoo extension beyond the 5 driver configs: GateNet (ECCV 2020,
    lartpang et al.) — gated skip connections + dilated-pyramid
    bridge, 5-level deep supervision."""
    return ExperimentConfig(
        name="gatenet_vgg16",
        data=DataConfig(dataset="duts", image_size=(320, 320)),
        model=ModelConfig(name="gatenet", backbone="vgg16"),
        loss=LossConfig(bce=1.0, iou=1.0, ssim=1.0, deep_supervision=True,
                        fused_kernel=True),
        optim=OptimConfig(optimizer="sgd", lr=0.01, momentum=0.9,
                          weight_decay=5e-4, schedule="poly",
                          warmup_steps=200),
        global_batch_size=32,
        mesh=MeshConfig(data=-1, model=1, seq=1),
    )


@register_config("vit_sod_sp")
def vit_sod_sp() -> ExperimentConfig:
    """Long-context member: global-attention ViT-SOD, trainable with
    the sequence-parallel step (--set mesh.seq=N shards image rows /
    token blocks over N devices; ring attention crosses them).  SSIM
    defaults off here for parity with the historical recipe, but the
    full hybrid loss IS supported under SP since the row-halo exchange
    (parallel/sp.py::_sp_ssim_loss) — enable with --set loss.ssim=1."""
    return ExperimentConfig(
        name="vit_sod_sp",
        data=DataConfig(dataset="duts", image_size=(320, 320)),
        model=ModelConfig(name="vit_sod", backbone="small", sync_bn=False),
        loss=LossConfig(bce=1.0, iou=1.0, ssim=0.0),
        optim=OptimConfig(optimizer="adamw", lr=3e-4, weight_decay=0.01,
                          warmup_steps=500),
        global_batch_size=16,
        mesh=MeshConfig(data=-1, model=1, seq=1),
    )
