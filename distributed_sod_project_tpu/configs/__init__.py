from .base import (
    DataConfig,
    ExperimentConfig,
    LossConfig,
    MeshConfig,
    ModelConfig,
    OptimConfig,
    ServeConfig,
    apply_overrides,
    config_from_dict,
    get_config,
    list_configs,
    register_config,
    validate_steps_per_dispatch,
)
from . import experiments  # noqa: F401  (populates the registry)

__all__ = [
    "DataConfig",
    "ExperimentConfig",
    "LossConfig",
    "MeshConfig",
    "ModelConfig",
    "OptimConfig",
    "ServeConfig",
    "apply_overrides",
    "config_from_dict",
    "get_config",
    "list_configs",
    "register_config",
    "validate_steps_per_dispatch",
]
