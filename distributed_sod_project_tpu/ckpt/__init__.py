from .manager import CheckpointManager, restore_latest

__all__ = ["CheckpointManager", "restore_latest"]
