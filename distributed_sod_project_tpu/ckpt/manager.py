"""Checkpoint/resume via orbax (SURVEY.md §2 C11, §3.4, §5).

The reference saves ``{model, optimizer, epoch}`` state_dicts from rank 0
and restores with ``map_location`` (SURVEY.md §3.4).  The TPU-native
replacement is orbax-checkpoint: multi-host-safe (every host
participates in the save of its addressable shards — there is no
"rank 0 only" dance), async (the save runs behind the next train steps),
and restore is sharding-aware: passing a template whose leaves carry
``NamedSharding``s places restored shards directly on device.

One checkpoint = the whole ``TrainState`` pytree (step / params /
batch_stats / opt_state) — exact resume, including optimizer momentum,
matching §4's "save→restore→bitwise-state equality" test contract.

Integrity (resilience/integrity.py): orbax's own ``latest_step()``
trusts any digit-named dir, including one whose finalize was killed by
preemption — restoring that crashes the run (reproduced on orbax
0.7.0).  This manager validates step dirs, writes size manifests after
saves finalize, and exposes :meth:`restore_latest_valid`, which
quarantines corrupt dirs and falls back to the newest VALID checkpoint
instead of raising.  docs/RESILIENCE.md has the failure-mode table.
"""

from __future__ import annotations

import json
import os
from typing import Any, List, Optional, Tuple

import orbax.checkpoint as ocp

from ..resilience import integrity
from ..utils.logging import get_logger


class CheckpointManager:
    """Thin policy wrapper over ``ocp.CheckpointManager``.

    - ``keep`` newest checkpoints are retained (reference kept every
      epoch; bounded retention is the TPU-pod-storage-friendly default).
    - ``best_metric``/``best_mode`` optionally switch retention to
      best-k by a metric reported at save time (the reference's
      "best-metric save", SURVEY.md §3.4).
    - saves are async: ``wait()`` blocks until durable (called before
      process exit and in tests).
    - ``latest_step``/``restore_latest_valid`` skip tmp/incomplete/
      corrupt step dirs (resilience/integrity.py) so a
      preemption-truncated save can never be selected as the resume
      point.
    """

    def __init__(
        self,
        directory: str,
        keep: int = 3,
        save_interval_steps: int = 1,
        best_metric: Optional[str] = None,
        best_mode: str = "max",
        async_save: bool = True,
    ):
        directory = os.path.abspath(directory)
        os.makedirs(directory, exist_ok=True)
        self.directory = directory
        opts = ocp.CheckpointManagerOptions(
            max_to_keep=keep,
            save_interval_steps=save_interval_steps,
            best_fn=(lambda m: m[best_metric]) if best_metric else None,
            best_mode=best_mode,
            enable_async_checkpointing=async_save,
        )
        self._mgr = ocp.CheckpointManager(directory, options=opts)

    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, str(int(step)))

    def save(self, step: int, state: Any, metrics: Optional[dict] = None,
             force: bool = False) -> bool:
        """Queue an async save of ``state`` at ``step``; returns whether a
        save was actually started (save_interval/keep policy may skip)."""
        import jax

        will_save = force
        if not will_save:
            try:
                will_save = bool(self._mgr.should_save(int(step)))
            except Exception:  # noqa: BLE001 — older orbax: assume yes
                will_save = True
        if will_save and jax.default_backend() == "cpu":
            # CPU backend: device arrays ALIAS host memory, so orbax's
            # async write can read buffers the next (donated) train
            # step has already updated in place — a torn checkpoint
            # whose step dir lies about its contents (observed: a
            # step-2 dir holding step-3 state).  Snapshot first; real
            # accelerators do a genuine D2H copy inside save(), so they
            # keep the zero-copy async path.  Fully-addressable leaves
            # snapshot to host numpy; multi-process global arrays
            # (spanning hosts) take an on-device copy instead — a fresh
            # buffer nothing ever donates, same-sharding, and every
            # process reaches save() together so the collective copy is
            # well-formed.
            import jax.numpy as _jnp
            import numpy as _np

            def _snap(x):
                if not hasattr(x, "dtype"):
                    return x
                if getattr(x, "is_fully_addressable", True):
                    return _np.array(x)
                return _jnp.copy(x)

            state = jax.tree_util.tree_map(_snap, state)
        metrics = {k: float(v) for k, v in (metrics or {}).items()}
        started = self._mgr.save(
            int(step),
            args=ocp.args.StandardSave(state),
            metrics=metrics or None,
            force=force,
        )
        # Earlier saves have finalized by now (orbax serializes async
        # saves); manifest them so restore can verify sizes.  THIS
        # step's manifest lands at the next save/wait.
        self._write_pending_manifests(exclude=int(step))
        # Fault injection (chaos suite): truncate this step the way a
        # mid-finalize preemption does.  No-op without DSOD_FAULTS, and
        # the synchronous wait only happens when THIS step is scheduled
        # for truncation — any other plan must leave save timing
        # untouched or the chaos runs would not exercise the real async
        # save path.
        from ..resilience.inject import plan_from_env

        plan = plan_from_env()
        if (plan is not None and started
                and int(step) in plan.truncate_steps):
            self._mgr.wait_until_finished()
            plan.maybe_truncate_ckpt(int(step), self._step_dir(step))
        return started

    def _write_pending_manifests(self, exclude: Optional[int] = None):
        for step, path in integrity.list_step_dirs(self.directory).items():
            if step == exclude or integrity.has_manifest(path):
                continue
            if os.path.isfile(os.path.join(path, "_CHECKPOINT_METADATA")):
                integrity.write_manifest(path)

    def restore(self, template: Any, step: Optional[int] = None) -> Any:
        """Restore the state saved at ``step`` (default: latest valid).

        ``template`` is a concrete or abstract ``TrainState`` with the
        target shapes/dtypes/shardings (build it with
        ``create_train_state`` + ``jax.eval_shape`` on the real configs).
        """
        if step is None:
            step = self.latest_step()
        if step is None:
            raise FileNotFoundError(
                f"no checkpoint found under {self.directory}")
        return self._mgr.restore(
            int(step), args=ocp.args.StandardRestore(template))

    def restore_raw(self, step: int) -> Any:
        """Template-free restore: the saved pytree as host arrays (the
        inspection/test path — no shardings, no dtype casting)."""
        return self._mgr.restore(
            int(step), args=ocp.args.StandardRestore())

    def restore_latest_valid(self, template: Any,
                             max_fallbacks: int = 2) -> Tuple[Any, Optional[int]]:
        """Restore the newest checkpoint that validates AND restores.

        Corrupt candidates are quarantined (moved under
        ``_quarantine/``, never deleted) and the next-newest is tried,
        so one truncated save costs ``checkpoint_every_steps`` of
        recompute instead of the whole run.  Returns
        ``(state, step)`` or ``(template, None)`` when nothing valid
        remains.

        ``max_fallbacks`` bounds the blast radius: per-dir corruption
        is localized, so after that many restore failures in one call
        the error is systemic (template shape mismatch, storage outage)
        and the last one re-raises instead of serially quarantining
        every good checkpoint and silently restarting from scratch.
        """
        log = get_logger()
        self.quarantine_invalid()
        fallbacks = 0
        for step in sorted(self.valid_steps(), reverse=True):
            try:
                return self.restore(template, step), step
            except Exception as e:  # noqa: BLE001 — quarantine + fall back
                if fallbacks >= max_fallbacks:
                    log.error(
                        "checkpoint step %d is consecutive restore "
                        "failure #%d (%r) — systemic, re-raising "
                        "instead of quarantining further", step,
                        fallbacks + 1, e)
                    raise
                fallbacks += 1
                path = self._step_dir(step)
                reason = f"validated but failed restore: {e!r}"
                log.warning("checkpoint step %d %s — quarantining",
                            step, reason)
                integrity.quarantine_step_dir(path, reason)
                self.reload()
        return template, None

    def latest_step(self) -> Optional[int]:
        """Newest VALID step (tmp/incomplete/corrupt dirs skipped)."""
        steps = self.valid_steps()
        return max(steps) if steps else None

    def valid_steps(self) -> List[int]:
        """Steps whose dirs pass integrity validation, ascending."""
        out = []
        for step, path in sorted(
                integrity.list_step_dirs(self.directory).items()):
            ok, reason = integrity.validate_step_dir(path)
            if ok:
                out.append(step)
            else:
                get_logger().warning(
                    "skipping checkpoint step %d: %s", step, reason)
        return out

    def quarantine_invalid(self) -> List[int]:
        """Move every step dir that fails validation under
        ``_quarantine/`` (evidence kept for post-mortem); returns the
        quarantined steps."""
        gone = []
        for step, path in sorted(
                integrity.list_step_dirs(self.directory).items()):
            ok, reason = integrity.validate_step_dir(path)
            if not ok:
                if integrity.quarantine_step_dir(path, reason):
                    gone.append(step)
                    get_logger().warning(
                        "quarantined checkpoint step %d: %s", step, reason)
        if gone:
            self.reload()
        return gone

    def all_steps(self):
        return sorted(self._mgr.all_steps())

    def reload(self):
        """Re-scan the directory (after quarantine moved dirs aside —
        orbax caches its step list)."""
        if hasattr(self._mgr, "reload"):
            self._mgr.reload()

    def wait(self):
        self._mgr.wait_until_finished()
        self._write_pending_manifests()

    def close(self):
        self.wait()
        self._mgr.close()

    # --- config sidecar -------------------------------------------------
    # The experiment config is stored as JSON next to the step dirs so a
    # checkpoint is self-describing (exact-resume per configs/base.py).

    def save_config(self, cfg) -> None:
        import dataclasses

        path = os.path.join(self.directory, "config.json")
        with open(path, "w") as f:
            json.dump(dataclasses.asdict(cfg), f, indent=2, default=str)

    def load_config_dict(self) -> Optional[dict]:
        path = os.path.join(self.directory, "config.json")
        if not os.path.exists(path):
            return None
        with open(path) as f:
            return json.load(f)


def restore_latest(directory: str, template: Any) -> Tuple[Any, Optional[int]]:
    """Convenience for ``--resume``: returns ``(state, step)`` from the
    newest VALID checkpoint (corrupt ones quarantined), or
    ``(template, None)`` if none exists yet."""
    mgr = CheckpointManager(directory, async_save=False)
    try:
        return mgr.restore_latest_valid(template)
    finally:
        mgr.close()
