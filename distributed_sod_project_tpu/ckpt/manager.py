"""Checkpoint/resume via orbax (SURVEY.md §2 C11, §3.4, §5).

The reference saves ``{model, optimizer, epoch}`` state_dicts from rank 0
and restores with ``map_location`` (SURVEY.md §3.4).  The TPU-native
replacement is orbax-checkpoint: multi-host-safe (every host
participates in the save of its addressable shards — there is no
"rank 0 only" dance), async (the save runs behind the next train steps),
and restore is sharding-aware: passing a template whose leaves carry
``NamedSharding``s places restored shards directly on device.

One checkpoint = the whole ``TrainState`` pytree (step / params /
batch_stats / opt_state) — exact resume, including optimizer momentum,
matching §4's "save→restore→bitwise-state equality" test contract.
"""

from __future__ import annotations

import json
import os
from typing import Any, Optional, Tuple

import orbax.checkpoint as ocp


class CheckpointManager:
    """Thin policy wrapper over ``ocp.CheckpointManager``.

    - ``keep`` newest checkpoints are retained (reference kept every
      epoch; bounded retention is the TPU-pod-storage-friendly default).
    - ``best_metric``/``best_mode`` optionally switch retention to
      best-k by a metric reported at save time (the reference's
      "best-metric save", SURVEY.md §3.4).
    - saves are async: ``wait()`` blocks until durable (called before
      process exit and in tests).
    """

    def __init__(
        self,
        directory: str,
        keep: int = 3,
        save_interval_steps: int = 1,
        best_metric: Optional[str] = None,
        best_mode: str = "max",
        async_save: bool = True,
    ):
        directory = os.path.abspath(directory)
        os.makedirs(directory, exist_ok=True)
        self.directory = directory
        opts = ocp.CheckpointManagerOptions(
            max_to_keep=keep,
            save_interval_steps=save_interval_steps,
            best_fn=(lambda m: m[best_metric]) if best_metric else None,
            best_mode=best_mode,
            enable_async_checkpointing=async_save,
        )
        self._mgr = ocp.CheckpointManager(directory, options=opts)

    def save(self, step: int, state: Any, metrics: Optional[dict] = None,
             force: bool = False) -> bool:
        """Queue an async save of ``state`` at ``step``; returns whether a
        save was actually started (save_interval/keep policy may skip)."""
        metrics = {k: float(v) for k, v in (metrics or {}).items()}
        return self._mgr.save(
            int(step),
            args=ocp.args.StandardSave(state),
            metrics=metrics or None,
            force=force,
        )

    def restore(self, template: Any, step: Optional[int] = None) -> Any:
        """Restore the state saved at ``step`` (default: latest).

        ``template`` is a concrete or abstract ``TrainState`` with the
        target shapes/dtypes/shardings (build it with
        ``create_train_state`` + ``jax.eval_shape`` on the real configs).
        """
        if step is None:
            step = self.latest_step()
        if step is None:
            raise FileNotFoundError(
                f"no checkpoint found under {self.directory}")
        return self._mgr.restore(
            int(step), args=ocp.args.StandardRestore(template))

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self):
        return sorted(self._mgr.all_steps())

    def wait(self):
        self._mgr.wait_until_finished()

    def close(self):
        self.wait()
        self._mgr.close()

    # --- config sidecar -------------------------------------------------
    # The experiment config is stored as JSON next to the step dirs so a
    # checkpoint is self-describing (exact-resume per configs/base.py).

    def save_config(self, cfg) -> None:
        import dataclasses

        path = os.path.join(self.directory, "config.json")
        with open(path, "w") as f:
            json.dump(dataclasses.asdict(cfg), f, indent=2, default=str)

    def load_config_dict(self) -> Optional[dict]:
        path = os.path.join(self.directory, "config.json")
        if not os.path.exists(path):
            return None
        with open(path) as f:
            return json.load(f)


def restore_latest(directory: str, template: Any) -> Tuple[Any, Optional[int]]:
    """Convenience for ``--resume``: returns ``(state, step)`` from the
    newest checkpoint, or ``(template, None)`` if none exists yet."""
    mgr = CheckpointManager(directory, async_save=False)
    try:
        step = mgr.latest_step()
        if step is None:
            return template, None
        return mgr.restore(template, step), step
    finally:
        mgr.close()
