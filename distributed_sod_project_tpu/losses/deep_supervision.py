"""Deep-supervision loss wrapper (SURVEY.md §2 C8, §3.1 hot loop).

The zoo convention is that every model returns a list of
full-resolution logit maps (U²-Net/BASNet: 7 side outputs; MINet: 1).
The wrapper sums the configured hybrid loss over every level — the
whole thing stays inside the compiled train step, so multi-level loss
costs one fused reduction pass, not N kernel launches.
"""

from __future__ import annotations

from typing import Dict, Sequence, Tuple

import jax.numpy as jnp

from .elementwise import bce_with_logits
from .region import cel_loss, iou_loss
from .ssim import ssim_loss


def deep_supervision_loss(
    logits_list: Sequence[jnp.ndarray],
    target: jnp.ndarray,
    *,
    bce_w: float = 1.0,
    iou_w: float = 1.0,
    ssim_w: float = 1.0,
    cel_w: float = 0.0,
    ssim_window: int = 11,
    level_weights: Sequence[float] | None = None,
    fused: bool = False,
) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """Σ_levels w_l · (bce_w·BCE + iou_w·IoU + ssim_w·SSIM + cel_w·CEL).

    Returns (total, components) where components holds the per-term sums
    across levels for logging.  ``fused=True`` routes the BCE/IoU/CEL
    terms through the Pallas single-pass reduction kernel
    (``pallas/fused_loss.py``; numerically identical, logged as one
    combined ``bce_iou_cel`` component).
    """
    if level_weights is None:
        level_weights = [1.0] * len(logits_list)
    total = jnp.float32(0.0)
    comps: Dict[str, jnp.ndarray] = {}

    def add(name, value, weight):
        nonlocal total
        comps[name] = comps.get(name, jnp.float32(0.0)) + value
        total = total + weight * value

    for logit, lw in zip(logits_list, level_weights):
        if fused:
            from ..pallas.fused_loss import fused_loss_available
        # Availability guard, not an error: fused=True configs must
        # keep working at off-lane eval sizes and on non-TPU backends
        # (falling back to the numerically-identical reference terms).
        if (fused and (bce_w or iou_w or cel_w)
                and fused_loss_available(logit.shape)):
            from ..pallas import fused_bce_iou_cel

            add("bce_iou_cel",
                lw * fused_bce_iou_cel(logit, target, bce_w, iou_w, cel_w),
                1.0)
        else:
            if bce_w:
                add("bce", lw * bce_with_logits(logit, target), bce_w)
            if iou_w:
                add("iou", lw * iou_loss(logit, target), iou_w)
            if cel_w:
                add("cel", lw * cel_loss(logit, target), cel_w)
        if ssim_w:
            if fused:
                from ..pallas.fused_ssim import (fused_ssim_available,
                                                 fused_ssim_loss)
            # Odd windows only: the kernel's analytic backward needs
            # symmetric taps (pallas/fused_ssim.py).
            if (fused and ssim_window % 2 == 1
                    and fused_ssim_available(logit.shape)):
                add("ssim", lw * fused_ssim_loss(
                    logit, target, window_size=ssim_window), ssim_w)
            else:
                add("ssim", lw * ssim_loss(
                    logit, target, window_size=ssim_window), ssim_w)
    comps["total"] = total
    return total, comps
