"""Region-level losses: soft IoU and MINet's consistency-enhanced loss
(SURVEY.md §2 C8; the BASNet hybrid-loss IoU term and the CEL term from
the MINet paper — reference unreadable, see SURVEY.md banner)."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _flatten_per_image(x):
    return x.reshape(x.shape[0], -1)


def iou_loss(logits, targets, *, eps: float = 1.0):
    """Soft Jaccard loss, per image then averaged: 1 − (∩+ε)/(∪+ε)."""
    p = jax.nn.sigmoid(logits.astype(jnp.float32))
    t = targets.astype(jnp.float32)
    p, t = _flatten_per_image(p), _flatten_per_image(t)
    inter = (p * t).sum(-1)
    union = p.sum(-1) + t.sum(-1) - inter
    return (1.0 - (inter + eps) / (union + eps)).mean()


def cel_loss(logits, targets, *, eps: float = 1e-6):
    """Consistency-enhanced loss (MINet):

        CEL = (Σp + Σt − 2Σpt) / (Σp + Σt)

    i.e. symmetric-difference mass over total mass, per image then
    averaged.  Differentiable and scale-invariant, pushing predictions
    toward whole-object consistency rather than per-pixel agreement.
    """
    p = jax.nn.sigmoid(logits.astype(jnp.float32))
    t = targets.astype(jnp.float32)
    p, t = _flatten_per_image(p), _flatten_per_image(t)
    inter = (p * t).sum(-1)
    total = p.sum(-1) + t.sum(-1)
    return ((total - 2.0 * inter) / (total + eps)).mean()
