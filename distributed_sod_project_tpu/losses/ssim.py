"""SSIM structural loss (SURVEY.md §2 C8, §7.3 hard part 4).

The BASNet-style hybrid loss uses 1 − SSIM with an 11×11 Gaussian
window (σ=1.5) computed on sigmoid probabilities.  TPU-first design:
all five windowed moments (E[a], E[b], E[a²], E[b²], E[ab]) are stacked
into channels and blurred by ONE pair of separable depthwise
convolutions (``feature_group_count``), so the input maps are read from
HBM once instead of five times; everything reduces in float32.  Oracle:
torch-cpu in tests/test_losses.py.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

_C1 = 0.01**2
_C2 = 0.03**2


def gaussian_window(size: int = 11, sigma: float = 1.5, dtype=jnp.float32):
    """1-D Gaussian taps, normalised to sum 1 (matches the de-facto
    pytorch_ssim construction: gauss(x) ∝ exp(−(x−⌊s/2⌋)²/2σ²))."""
    x = jnp.arange(size, dtype=dtype) - size // 2
    g = jnp.exp(-(x**2) / (2.0 * sigma**2))
    return g / g.sum()


def _blur(x, win1d):
    """Separable depthwise Gaussian blur, NHWC, 'SAME' zero padding."""
    c = x.shape[-1]
    kh = jnp.tile(win1d[:, None, None, None], (1, 1, 1, c))  # HWIO, I=1
    kw = jnp.tile(win1d[None, :, None, None], (1, 1, 1, c))
    dn = lax.conv_dimension_numbers(x.shape, kh.shape, ("NHWC", "HWIO", "NHWC"))
    pad_h = [(win1d.shape[0] // 2,) * 2, (0, 0)]
    pad_w = [(0, 0), (win1d.shape[0] // 2,) * 2]
    x = lax.conv_general_dilated(
        x, kh, (1, 1), pad_h, dimension_numbers=dn, feature_group_count=c
    )
    x = lax.conv_general_dilated(
        x, kw, (1, 1), pad_w, dimension_numbers=dn, feature_group_count=c
    )
    return x


def ssim(a, b, *, window_size: int = 11, sigma: float = 1.5):
    """Mean SSIM map between ``a`` and ``b`` (NHWC, any channel count)."""
    a = a.astype(jnp.float32)
    b = b.astype(jnp.float32)
    win = gaussian_window(window_size, sigma)
    c = a.shape[-1]
    # One blur over the 5 stacked moment maps instead of 5 blurs.
    stack = jnp.concatenate([a, b, a * a, b * b, a * b], axis=-1)
    blurred = _blur(stack, win)
    mu_a, mu_b, e_aa, e_bb, e_ab = (
        blurred[..., i * c:(i + 1) * c] for i in range(5))
    mu_aa, mu_bb, mu_ab = mu_a * mu_a, mu_b * mu_b, mu_a * mu_b
    var_a = e_aa - mu_aa
    var_b = e_bb - mu_bb
    cov = e_ab - mu_ab
    num = (2.0 * mu_ab + _C1) * (2.0 * cov + _C2)
    den = (mu_aa + mu_bb + _C1) * (var_a + var_b + _C2)
    return (num / den).mean()


def ssim_loss(logits, targets, *, window_size: int = 11, sigma: float = 1.5):
    """1 − SSIM(sigmoid(logits), targets)."""
    p = jax.nn.sigmoid(logits.astype(jnp.float32))
    return 1.0 - ssim(p, targets.astype(jnp.float32),
                      window_size=window_size, sigma=sigma)
