"""Pixel-wise losses (SURVEY.md §2 C8).

All losses take full-resolution logits [B,H,W,1] and binary targets of
the same shape, reduce in float32 (bf16 activations upstream are fine;
reductions are where precision dies on TPU), and return scalars.
"""

from __future__ import annotations

import jax.numpy as jnp


def bce_with_logits(logits, targets, *, reduction: str = "mean"):
    """Numerically stable sigmoid binary cross-entropy.

    max(x,0) - x*t + log(1+exp(-|x|)) — the standard stable form; never
    materialises sigmoid(x), so it is fusion-friendly under XLA.
    """
    x = logits.astype(jnp.float32)
    t = targets.astype(jnp.float32)
    per_pixel = jnp.maximum(x, 0.0) - x * t + jnp.log1p(jnp.exp(-jnp.abs(x)))
    if reduction == "mean":
        return per_pixel.mean()
    if reduction == "sum":
        return per_pixel.sum()
    if reduction == "none":
        return per_pixel
    raise ValueError(f"unknown reduction {reduction!r}")
