from .elementwise import bce_with_logits
from .region import cel_loss, iou_loss
from .ssim import ssim, ssim_loss
from .deep_supervision import deep_supervision_loss

__all__ = [
    "bce_with_logits",
    "cel_loss",
    "iou_loss",
    "ssim",
    "ssim_loss",
    "deep_supervision_loss",
]
