#!/usr/bin/env python
"""Test/eval entrypoint (SURVEY.md §2 C2, §3.2; [B:5] `test.py --device`).

    python test.py --config minet_r50_dp --ckpt-dir runs/minet --device tpu \
        --save-dir preds/ --data-root /data/DUTS-TE

Loads the newest checkpoint, sweeps every test set (resize → forward →
sigmoid → resize-back → PNG), and prints the metric dict (max-Fβ, MAE,
S/E-measure) as JSON.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--config", default=None,
                   help="registered config name (default: read the "
                        "checkpoint's own config.json sidecar)")
    p.add_argument("--ckpt-dir", required=True,
                   help="directory of checkpoints written by train.py")
    p.add_argument("--step", type=int, default=None,
                   help="checkpoint step (default: newest)")
    p.add_argument("--device", default=None, choices=["tpu", "cpu", None])
    p.add_argument("--data-root", default=None,
                   help="test-set root; repeatable as name=path",
                   action="append")
    p.add_argument("--save-dir", default=None, help="write saliency PNGs here")
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--no-structure", action="store_true",
                   help="skip S/E-measure (faster)")
    p.add_argument("--fast-metrics", action="store_true",
                   help="accumulate Fβ/Em/MAE on-device at the eval "
                        "resolution instead of the host-side "
                        "original-resolution convention — much faster, "
                        "slightly different numbers (PySODMetrics "
                        "scores at each image's native size)")
    p.add_argument("--tta", action="store_true",
                   help="average in the horizontally-flipped prediction "
                        "(2x forward cost)")
    p.add_argument("--set", dest="overrides", action="append", default=[],
                   metavar="PATH=VALUE",
                   help="dotted config override (repeatable)")
    return p.parse_args(argv)


def main(argv=None):
    args = parse_args(argv)

    from distributed_sod_project_tpu.utils.platform import select_platform

    select_platform(args.device)

    import jax

    from distributed_sod_project_tpu.data import resolve_dataset
    from distributed_sod_project_tpu.eval import evaluate
    from distributed_sod_project_tpu.eval.inference import restore_for_eval

    cfg, model, state = restore_for_eval(
        args.ckpt_dir, config_name=args.config, overrides=args.overrides,
        step=args.step)

    # Named test sets: ["duts_te=/data/DUTS-TE", ...]; default config set.
    datasets = None
    if args.data_root:
        datasets = {}
        for spec in args.data_root:
            name, _, path = spec.rpartition("=")
            name = name or os.path.basename(path.rstrip("/")) or "test"
            datasets[name] = resolve_dataset(
                dataclasses.replace(cfg.data, root=path))

    from distributed_sod_project_tpu.parallel.mesh import make_mesh

    # All local chips share every eval batch (data-sharded forward).
    mesh = make_mesh(cfg.mesh) if jax.device_count() > 1 else None
    results = evaluate(cfg, state, model=model, mesh=mesh, datasets=datasets,
                       save_root=args.save_dir, batch_size=args.batch_size,
                       compute_structure=not args.no_structure,
                       tta=args.tta, device_metrics=args.fast_metrics)
    print(json.dumps(results, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
