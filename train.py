#!/usr/bin/env python
"""Train entrypoint (SURVEY.md §2 C1, §3.1; [B:5] `train.py --device`).

    python train.py --config minet_r50_dp --device tpu
    python train.py --config u2net_ds --data-root /data/DUTS-TR --resume

Multi-host pods: launch the same command on every host (with
``--distributed`` to run ``jax.distributed.initialize``); the mesh spans
all chips — the TPU replacement for torchrun + init_process_group
(SURVEY.md §3.5).
"""

from __future__ import annotations

import argparse
import dataclasses
import os
import sys


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--config", default=None, help="registered config name")
    p.add_argument("--list-configs", action="store_true",
                   help="print registered configs and exit")
    p.add_argument("--device", default=None, choices=["tpu", "cpu", None],
                   help="force a JAX platform (default: auto)")
    p.add_argument("--workdir", default=None, help="checkpoint/log dir")
    p.add_argument("--resume", action="store_true",
                   help="resume from the newest checkpoint in workdir")
    p.add_argument("--data-root", default=None,
                   help="dataset root (overrides config; falls back to "
                        "synthetic data when absent)")
    p.add_argument("--batch-size", type=int, default=None)
    p.add_argument("--epochs", type=int, default=None)
    p.add_argument("--max-steps", type=int, default=None,
                   help="truncate training (smoke runs)")
    p.add_argument("--lr", type=float, default=None)
    p.add_argument("--seed", type=int, default=None)
    p.add_argument("--distributed", action="store_true",
                   help="multi-host: run jax.distributed.initialize()")
    p.add_argument("--set", dest="overrides", action="append", default=[],
                   metavar="PATH=VALUE",
                   help="dotted config override, e.g. --set optim.lr=0.01 "
                        "--set data.image_size=256,256 (repeatable)")
    p.add_argument("--profile-dir", default=None,
                   help="capture a jax.profiler trace of a post-warmup "
                        "step window into this directory")
    p.add_argument("--telemetry-port", type=int, default=None,
                   help="start the trainer telemetry sidecar on this "
                        "port (0 = ephemeral): /metrics, /healthz, "
                        "/debug/traces, /debug/profile?seconds=N "
                        "(docs/OBSERVABILITY.md; overrides "
                        "cfg.telemetry_port)")
    p.add_argument("--telemetry-port-file", default=None,
                   help="write the sidecar's bound port here once "
                        "listening (atomic, for scripts)")
    p.add_argument("--eval-every", type=int, default=None,
                   help="run held-out eval every N steps (overrides "
                        "config eval_every_steps)")
    p.add_argument("--debug-nans", action="store_true",
                   help="jax.config debug_nans: every compiled step "
                        "re-checks for NaN production and fails loudly "
                        "at the producing op (slow — debugging only; "
                        "for production guards use optim.skip_nonfinite)")
    return p.parse_args(argv)


def main(argv=None):
    args = parse_args(argv)

    if args.list_configs:
        from distributed_sod_project_tpu.configs import get_config, list_configs

        for name in list_configs():
            cfg = get_config(name)
            print(f"{name:18s} model={cfg.model.name}/{cfg.model.backbone}"
                  f"  batch={cfg.global_batch_size}"
                  f"  data={cfg.data.dataset}")
        return 0
    if not args.config:
        raise SystemExit("--config is required (see --list-configs)")

    from distributed_sod_project_tpu.utils.platform import select_platform

    select_platform(args.device)

    import jax

    if args.debug_nans:
        jax.config.update("jax_debug_nans", True)
    if args.distributed:
        jax.distributed.initialize()

    from distributed_sod_project_tpu.configs import apply_overrides, get_config
    from distributed_sod_project_tpu.train.loop import fit

    cfg = get_config(args.config)
    cfg = apply_overrides(cfg, args.overrides)
    if args.data_root is not None:
        cfg = cfg.replace(data=dataclasses.replace(cfg.data, root=args.data_root))
    if args.batch_size is not None:
        cfg = cfg.replace(global_batch_size=args.batch_size)
    if args.epochs is not None:
        cfg = cfg.replace(num_epochs=args.epochs)
    if args.lr is not None:
        cfg = cfg.replace(optim=dataclasses.replace(cfg.optim, lr=args.lr))
    if args.seed is not None:
        cfg = cfg.replace(seed=args.seed)
    if args.eval_every is not None:
        cfg = cfg.replace(eval_every_steps=args.eval_every)

    metrics = fit(cfg, workdir=args.workdir, resume=args.resume,
                  max_steps=args.max_steps, profile_dir=args.profile_dir,
                  telemetry_port=args.telemetry_port,
                  telemetry_port_file=args.telemetry_port_file)
    print({k: round(v, 4) if isinstance(v, float) else v
           for k, v in metrics.items()})
    return 0


if __name__ == "__main__":
    sys.exit(main())
