// dsod_host — native host-side data plane for the TPU SOD framework.
//
// Replaces the reference's DataLoader worker-process decode path
// (SURVEY.md §2 C7, §2.2 "DALI-style / libjpeg decode in DataLoader
// workers") with an in-process C++ pipeline: libjpeg/libpng decode →
// half-pixel bilinear resize → (optional hflip) → ImageNet
// normalisation, parallelised over a batch with std::thread.  TPU hosts
// feed many chips from one process; decode must not hold the GIL, so
// the whole batch path is C++ and Python only sees the filled
// float32 NHWC buffer (ctypes, zero copies beyond the decode itself).
//
// C ABI (see data/native.py):
//   dsod_decode_batch(paths, n, H, W, gray, hflip_mask, mean, std, out)
//     → 0 on success, else 1-based index of the first failed item.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <csetjmp>
#include <string>
#include <thread>
#include <vector>
#include <atomic>

#include <jpeglib.h>
#include <png.h>

namespace {

struct Image {
  int w = 0, h = 0, c = 0;     // c: 1 or 3
  std::vector<uint8_t> data;   // HWC, row-major
};

// ---------------------------------------------------------------- JPEG
struct JpegErr {
  jpeg_error_mgr mgr;
  jmp_buf jb;
};

void jpeg_err_exit(j_common_ptr cinfo) {
  longjmp(reinterpret_cast<JpegErr*>(cinfo->err)->jb, 1);
}

bool decode_jpeg(FILE* f, bool gray, Image* out) {
  jpeg_decompress_struct cinfo;
  JpegErr jerr;
  cinfo.err = jpeg_std_error(&jerr.mgr);
  jerr.mgr.error_exit = jpeg_err_exit;
  if (setjmp(jerr.jb)) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_stdio_src(&cinfo, f);
  jpeg_read_header(&cinfo, TRUE);
  cinfo.out_color_space = gray ? JCS_GRAYSCALE : JCS_RGB;
  jpeg_start_decompress(&cinfo);
  out->w = cinfo.output_width;
  out->h = cinfo.output_height;
  out->c = cinfo.output_components;
  out->data.resize(size_t(out->w) * out->h * out->c);
  const size_t stride = size_t(out->w) * out->c;
  while (cinfo.output_scanline < cinfo.output_height) {
    uint8_t* row = out->data.data() + stride * cinfo.output_scanline;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return true;
}

// ----------------------------------------------------------------- PNG
bool decode_png(FILE* f, bool gray, Image* out) {
  png_structp png = png_create_read_struct(PNG_LIBPNG_VER_STRING,
                                           nullptr, nullptr, nullptr);
  if (!png) return false;
  png_infop info = png_create_info_struct(png);
  if (!info) {
    png_destroy_read_struct(&png, nullptr, nullptr);
    return false;
  }
  if (setjmp(png_jmpbuf(png))) {
    png_destroy_read_struct(&png, &info, nullptr);
    return false;
  }
  png_init_io(png, f);
  png_read_info(png, info);
  // Normalise to 8-bit gray or RGB.
  png_set_strip_16(png);
  png_set_strip_alpha(png);
  png_set_packing(png);
  png_set_expand(png);
  if (gray) {
    if (png_get_color_type(png, info) & PNG_COLOR_MASK_COLOR)
      png_set_rgb_to_gray_fixed(png, 1, -1, -1);
  } else {
    if (!(png_get_color_type(png, info) & PNG_COLOR_MASK_COLOR))
      png_set_gray_to_rgb(png);
  }
  png_read_update_info(png, info);
  out->w = png_get_image_width(png, info);
  out->h = png_get_image_height(png, info);
  out->c = png_get_channels(png, info);
  out->data.resize(size_t(out->w) * out->h * out->c);
  std::vector<png_bytep> rows(out->h);
  const size_t stride = size_t(out->w) * out->c;
  for (int y = 0; y < out->h; ++y)
    rows[y] = out->data.data() + stride * y;
  png_read_image(png, rows.data());
  png_read_end(png, nullptr);
  png_destroy_read_struct(&png, &info, nullptr);
  return true;
}

// ------------------------------------------------------- PNG encode
// 8-bit grayscale writer for saliency maps (the test.py dump path —
// thousands of small PNGs per eval; SURVEY.md §3.2 hot loop).
bool encode_png_gray(const char* path, const uint8_t* data, int w, int h) {
  FILE* f = fopen(path, "wb");
  if (!f) return false;
  png_structp png = png_create_write_struct(PNG_LIBPNG_VER_STRING,
                                            nullptr, nullptr, nullptr);
  if (!png) {
    fclose(f);
    return false;
  }
  png_infop info = png_create_info_struct(png);
  if (!info) {
    png_destroy_write_struct(&png, nullptr);
    fclose(f);
    return false;
  }
  if (setjmp(png_jmpbuf(png))) {
    png_destroy_write_struct(&png, &info);
    fclose(f);
    return false;
  }
  png_init_io(png, f);
  png_set_IHDR(png, info, w, h, 8, PNG_COLOR_TYPE_GRAY,
               PNG_INTERLACE_NONE, PNG_COMPRESSION_TYPE_DEFAULT,
               PNG_FILTER_TYPE_DEFAULT);
  // Saliency maps are smooth: level 1 + SUB filter ≈ same size as
  // default at a fraction of the CPU time.
  png_set_compression_level(png, 1);
  png_set_filter(png, 0, PNG_FILTER_SUB);
  png_write_info(png, info);
  for (int y = 0; y < h; ++y)
    png_write_row(png, const_cast<png_bytep>(data + size_t(y) * w));
  png_write_end(png, nullptr);
  png_destroy_write_struct(&png, &info);
  fclose(f);
  return true;
}

bool decode_file(const char* path, bool gray, Image* out) {
  FILE* f = fopen(path, "rb");
  if (!f) return false;
  uint8_t magic[2] = {0, 0};
  if (fread(magic, 1, 2, f) != 2) {
    fclose(f);
    return false;
  }
  rewind(f);
  bool ok = false;
  if (magic[0] == 0xFF && magic[1] == 0xD8)
    ok = decode_jpeg(f, gray, out);
  else if (magic[0] == 0x89 && magic[1] == 0x50)
    ok = decode_png(f, gray, out);
  fclose(f);
  return ok && out->c == (gray ? 1 : 3);
}

// ------------------------------------------------- resize + normalise
// PIL-convention separable bilinear resampling: a triangle filter whose
// support scales with the downscale ratio (antialiased), identical in
// spirit to Pillow's ImagingResample with BILINEAR — so the native path
// and the PIL fallback produce matching training data.  For upscale the
// support clamps to 1 and this is classic half-pixel bilinear.
struct ResampleAxis {
  std::vector<int> lo;        // first source index per output index
  std::vector<int> n;         // taps per output index
  std::vector<float> w;       // taps, flattened, max_taps stride
  int max_taps = 0;
};

ResampleAxis build_axis(int in_size, int out_size) {
  ResampleAxis ax;
  const double scale = double(in_size) / out_size;
  const double fscale = scale < 1.0 ? 1.0 : scale;
  const double support = 1.0 * fscale;  // triangle filter support
  ax.max_taps = int(support) * 2 + 2;
  ax.lo.resize(out_size);
  ax.n.resize(out_size);
  ax.w.assign(size_t(out_size) * ax.max_taps, 0.0f);
  for (int o = 0; o < out_size; ++o) {
    const double center = (o + 0.5) * scale;
    // Pillow's window rounding (precompute_coeffs): ±support with +0.5.
    int lo = int(center - support + 0.5);
    if (lo < 0) lo = 0;
    int hi = int(center + support + 0.5);
    if (hi > in_size) hi = in_size;
    double sum = 0.0;
    std::vector<double> taps(hi - lo);
    for (int x = lo; x < hi; ++x) {
      double t = (x + 0.5 - center) / fscale;
      double v = t < 0 ? 1.0 + t : 1.0 - t;  // triangle
      if (v < 0) v = 0;
      taps[x - lo] = v;
      sum += v;
    }
    ax.lo[o] = lo;
    ax.n[o] = hi - lo;
    for (int k = 0; k < hi - lo; ++k)
      ax.w[size_t(o) * ax.max_taps + k] =
          float(sum > 0 ? taps[k] / sum : 0.0);
  }
  return ax;
}

void resize_normalize(const Image& im, int H, int W, bool hflip,
                      const float* mean, const float* stdv, float* out) {
  const int C = im.c;
  const ResampleAxis axx = build_axis(im.w, W);
  const ResampleAxis axy = build_axis(im.h, H);
  // Horizontal pass: [im.h, W, C] floats.
  std::vector<float> tmp(size_t(im.h) * W * C);
  for (int y = 0; y < im.h; ++y) {
    const uint8_t* src = im.data.data() + size_t(y) * im.w * C;
    float* dst = tmp.data() + size_t(y) * W * C;
    for (int o = 0; o < W; ++o) {
      const float* w = &axx.w[size_t(o) * axx.max_taps];
      for (int ch = 0; ch < C; ++ch) {
        float acc = 0.0f;
        for (int k = 0; k < axx.n[o]; ++k)
          acc += w[k] * src[(axx.lo[o] + k) * C + ch];
        dst[o * C + ch] = acc;
      }
    }
  }
  // Vertical pass + normalise + optional hflip on the write.
  for (int o = 0; o < H; ++o) {
    const float* w = &axy.w[size_t(o) * axy.max_taps];
    for (int x = 0; x < W; ++x) {
      int out_x = hflip ? (W - 1 - x) : x;
      float* dst = out + (size_t(o) * W + out_x) * C;
      for (int ch = 0; ch < C; ++ch) {
        float acc = 0.0f;
        for (int k = 0; k < axy.n[o]; ++k)
          acc += w[k] * tmp[(size_t(axy.lo[o] + k) * W + x) * C + ch];
        dst[ch] = (acc * (1.0f / 255.0f) - mean[ch]) / stdv[ch];
      }
    }
  }
}

}  // namespace

extern "C" {

// paths: n C-strings.  out: [n, H, W, C] f32 (C = gray ? 1 : 3).
// hflip_mask: n bytes (0/1) or nullptr.  mean/stdv: C floats.
// threads <= 0 → hardware_concurrency.  Returns 0 on success, else the
// 1-based index of the first item that failed to decode.
int dsod_decode_batch(const char** paths, int n, int H, int W, int gray,
                      const uint8_t* hflip_mask, const float* mean,
                      const float* stdv, float* out, int threads) {
  const int C = gray ? 1 : 3;
  const size_t item = size_t(H) * W * C;
  std::atomic<int> next(0), failed(0);
  int nt = threads > 0 ? threads
                       : int(std::thread::hardware_concurrency());
  if (nt < 1) nt = 1;
  if (nt > n) nt = n;
  auto worker = [&]() {
    for (int i = next.fetch_add(1); i < n; i = next.fetch_add(1)) {
      Image im;
      if (!decode_file(paths[i], gray != 0, &im)) {
        int expect = 0;
        failed.compare_exchange_strong(expect, i + 1);
        continue;
      }
      bool hf = hflip_mask && hflip_mask[i];
      resize_normalize(im, H, W, hf, mean, stdv, out + item * i);
    }
  };
  if (nt == 1) {
    worker();
  } else {
    std::vector<std::thread> pool;
    pool.reserve(nt);
    for (int t = 0; t < nt; ++t) pool.emplace_back(worker);
    for (auto& th : pool) th.join();
  }
  return failed.load();
}

// paths/data/ws/hs: n grayscale images, data[i] is hs[i]*ws[i] bytes.
// Returns 0 on success, else the 1-based index of the first failure.
int dsod_write_png_batch(const char** paths, const uint8_t* const* data,
                         const int* ws, const int* hs, int n, int threads) {
  std::atomic<int> next(0), failed(0);
  int nt = threads > 0 ? threads
                       : int(std::thread::hardware_concurrency());
  if (nt < 1) nt = 1;
  if (nt > n) nt = n;
  auto worker = [&]() {
    for (int i = next.fetch_add(1); i < n; i = next.fetch_add(1)) {
      if (!encode_png_gray(paths[i], data[i], ws[i], hs[i])) {
        int expect = 0;
        failed.compare_exchange_strong(expect, i + 1);
      }
    }
  };
  if (nt == 1) {
    worker();
  } else {
    std::vector<std::thread> pool;
    pool.reserve(nt);
    for (int t = 0; t < nt; ++t) pool.emplace_back(worker);
    for (auto& th : pool) th.join();
  }
  return failed.load();
}

int dsod_version() { return 2; }

}  // extern "C"
