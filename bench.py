#!/usr/bin/env python
"""Throughput benchmark — prints ONE JSON line for the driver.

Measures the governing metric (BASELINE.json:2): images/sec/chip for the
flagship data-parallel train step (MINet-ResNet50, 320×320, bf16), the
TPU analogue of the reference's 8×V100 DDP throughput posture.

``vs_baseline`` is self-relative: the reference's V100 number was
unobtainable (BASELINE.md), so the first recorded run seeds
``bench_baseline.json`` and later runs report the ratio against it.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
from functools import partial

# Exactly ONE result line may reach stdout (the driver parses the last
# JSON line).  The main thread and the watchdog timer thread race for
# it; an atomic claim (not a check-then-print) decides the winner.
_REPORT_LOCK = threading.Lock()
_REPORT_CLAIMED = False

# Per-config --batch-per-chip defaults.  128 is the flagship's measured
# v5e throughput optimum (batch sweep in BASELINE.md); the heavier zoo
# members (two-stream hdfnet, 89M-param basnet, 7-output u2net) were
# measured at 32 and risk HBM OOM at 128.  tools/bench_zoo.py reuses
# this table so sweeps and direct runs agree.
PER_CONFIG_BATCH = {"minet_r50_dp": 128}
DEFAULT_BATCH = 32

# Env vars that change the COMPILED PROGRAM (and therefore throughput):
# they must be part of the baseline key, or an A/B leg run with one of
# these set seeds the canonical key with the slow variant and every
# later run reports a bogus vs_baseline (the exact failure class the
# round-2 remat fix documented — see _report()).  Kept as an explicit
# literal on purpose: tools/dsodlint.py (env-coherence) cross-checks it
# against utils/envvars.py's program_affecting rows BOTH ways, so a new
# program-affecting knob that forgets either side fails lint.
_PROGRAM_ENV_VARS = (
    "DSOD_RESIZE_IMPL",
    "DSOD_RESIZE_INTERLEAVE",
    "DSOD_FLASH_BLOCK_Q",
    "DSOD_FLASH_BLOCK_KV",
    "DSOD_STEM_IMPL",
    "DSOD_DLF_VMEM_MB",
    "DSOD_RESAMPLE_VMEM_MB",
    "DSOD_CONV_VMEM_MB",
)


def _claim_report() -> bool:
    global _REPORT_CLAIMED
    with _REPORT_LOCK:
        if _REPORT_CLAIMED:
            return False
        _REPORT_CLAIMED = True
        return True


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--config", default="minet_r50_dp")
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--warmup", type=int, default=3)
    p.add_argument("--batch-per-chip", type=int, default=None,
                   help="per-chip batch (default: per-config — 128 for "
                        "the flagship, its measured v5e optimum; 32 for "
                        "the heavier zoo members, which risk HBM OOM at "
                        "b128 — see PER_CONFIG_BATCH.  Small batches "
                        "underreport: per-step dispatch latency "
                        "dominates under ~16 imgs/chip on remote-device "
                        "transports)")
    p.add_argument("--image-size", type=int, default=320)
    p.add_argument("--device", default=None, choices=["tpu", "cpu", None])
    p.add_argument("--mode", default="train",
                   choices=["train", "eval", "data", "serve"],
                   help="train: full DP step (default); eval: forward-only "
                        "sigmoid inference (the test.py hot loop); data: "
                        "host input pipeline only — no device work, batch "
                        "is --batch-per-chip as-is (select the backend "
                        "with --set data.backend=host|tfdata|grain); "
                        "serve: end-to-end HTTP serving latency — an "
                        "in-process server (random-init weights) driven "
                        "by the closed-loop load generator, --steps "
                        "requests total; reports imgs/sec plus "
                        "p50/p95/p99 ms so serving latency joins the "
                        "recorded perf trajectory (docs/SERVING.md)")
    p.add_argument("--set", dest="overrides", action="append", default=[],
                   metavar="PATH=VALUE",
                   help="dotted config override, e.g. --set "
                        "loss.fused_kernel=true --set model.remat=true "
                        "(bench always times the shard_map DP step)")
    p.add_argument("--steps-per-dispatch", type=int, default=1,
                   help="device-side step chunking sweep arm (train "
                        "mode only): fold k train steps into one "
                        "lax.scan dispatch (train.steps_per_dispatch); "
                        "--steps then counts DISPATCHES, each k steps "
                        "on a k-stacked resident batch.  Folded into "
                        "the vs_baseline key as a --set override, so "
                        "A/B legs never contaminate the canonical "
                        "k=1 baselines")
    p.add_argument("--profile-dir", default=None,
                   help="capture a jax.profiler trace of the timed window")
    p.add_argument("--baseline-file", default=None,
                   help="regression mode: JSON file of recorded "
                        "baselines keyed like bench_baseline.json.  "
                        "First run per key SEEDS the file; later runs "
                        "add a vs_recorded field (this run / recorded) "
                        "to the result line.  Unlike the implicit "
                        "bench_baseline.json side file, this one is "
                        "meant to be checked in (tools/bench_data.sh)")
    p.add_argument("--fail-below", type=float, default=0.0,
                   help="with --baseline-file: exit 3 when vs_recorded "
                        "falls below this ratio (0 = never gate — the "
                        "shared-CI posture; the number is still "
                        "printed and recorded)")
    p.add_argument("--watchdog", type=int, default=1800,
                   help="hard-exit with a diagnostic after this many "
                        "seconds (the remote-TPU transport can wedge "
                        "indefinitely; 0 disables)")
    p.add_argument("--init-retries", type=int, default=5,
                   help="MINIMUM attempts at backend init / first "
                        "compile when the device transport reports "
                        "UNAVAILABLE (round-1 postmortem: one transient "
                        "tunnel outage at jax.device_count() cost the "
                        "round its benchmark artifact).  On top of this "
                        "floor, retries continue until --retry-budget "
                        "seconds have elapsed")
    p.add_argument("--init-backoff", type=float, default=30.0,
                   help="seconds between retry attempts")
    p.add_argument("--retry-budget", type=float, default=None,
                   help="keep retrying backend init until this many "
                        "seconds have elapsed (default: watchdog - 300, "
                        "i.e. ~25 of the 30 watchdog minutes — round-2 "
                        "postmortem: 5 fixed attempts gave up with 15+ "
                        "unused minutes on the clock and the tunnel's "
                        "observed behavior is 'wedged now, back later "
                        "in the session'; 0 = exactly --init-retries "
                        "attempts)")
    p.add_argument("--probe-timeout", type=float, default=120.0,
                   help="per-attempt subprocess dial-probe timeout; the "
                        "transport's common failure mode is a WEDGE "
                        "(infinite hang inside PJRT client creation), "
                        "which only an out-of-process probe can turn "
                        "into a retryable failure (0 disables probing)")
    args = p.parse_args(argv)
    if args.warmup < 0:
        p.error("--warmup must be >= 0")
    if args.steps < 1:
        p.error("--steps must be >= 1")
    for flag in ("watchdog", "init_backoff", "probe_timeout"):
        if getattr(args, flag) < 0:
            p.error(f"--{flag.replace('_', '-')} must be >= 0")
    if args.retry_budget is not None and args.retry_budget < 0:
        p.error("--retry-budget must be >= 0")
    if args.batch_per_chip is None:
        args.batch_per_chip = PER_CONFIG_BATCH.get(args.config,
                                                   DEFAULT_BATCH)
    if args.batch_per_chip < 1:
        p.error("--batch-per-chip must be >= 1")
    if args.steps_per_dispatch < 1:
        p.error("--steps-per-dispatch must be >= 1")
    if args.steps_per_dispatch > 1:
        if args.mode != "train":
            p.error("--steps-per-dispatch only applies to --mode train")
        # Route through the config override machinery so the compiled
        # program AND the vs_baseline key both carry the knob (the
        # same contamination-proofing --set and _PROGRAM_ENV_VARS get).
        args.overrides = list(args.overrides) + [
            f"steps_per_dispatch={args.steps_per_dispatch}"]
    global _REPORT_CLAIMED  # in-process callers may run main() repeatedly
    _REPORT_CLAIMED = False

    timer = None
    if args.watchdog:

        def _abort():
            print(f"bench watchdog: no result after {args.watchdog}s — "
                  "device transport likely wedged (see "
                  "docs/PERFORMANCE.md tunnel notes)", file=sys.stderr,
                  flush=True)
            # Still hand the driver a parseable result line: a wedge
            # must not reproduce round 1's parsed=null artifact.  The
            # atomic claim inside _report_error guarantees it never
            # prints AFTER a genuine result line; if the main thread
            # claimed first, give it a moment to finish writing.
            if not _report_error(args, f"watchdog timeout after "
                                       f"{args.watchdog}s (device "
                                       "transport wedged)"):
                time.sleep(2)
            sys.stdout.flush()
            os._exit(0)

        timer = threading.Timer(args.watchdog, _abort)
        timer.daemon = True
        timer.start()

    try:
        if args.mode == "data":
            return _run(args)  # pure host path: no device to retry
        last_err = None
        min_attempts = max(args.init_retries, 1)
        budget = args.retry_budget
        if budget is None:
            # Spend (nearly) the whole watchdog window retrying: the
            # 300 s reserve leaves room for a final attempt's compile +
            # timed steps to finish before the watchdog fires.
            budget = max(args.watchdog - 300.0, 0.0) if args.watchdog else 0.0
        t_start = time.monotonic()
        attempt = 0
        while True:
            attempt += 1
            fail = None
            if args.probe_timeout and _expects_accelerator(args):
                fail = _probe_backend(args.probe_timeout)
            if fail is None:
                try:
                    return _run(args)
                except Exception as e:  # noqa: BLE001 — classified below
                    if not _is_unavailable(e):
                        # Non-retryable (OOM, shape error, bad flag):
                        # full traceback to stderr for the human, but
                        # the driver STILL gets a parsed JSON line —
                        # a bare raise is how round 1 lost its
                        # benchmark artifact to parsed=null.
                        import traceback

                        traceback.print_exc()
                        _report_error(
                            args, f"{type(e).__name__}: {str(e)[:300]}")
                        return 1
                    fail = str(e)
                    _reset_backends()
            last_err = fail
            elapsed = time.monotonic() - t_start
            print(f"bench: device backend unavailable (attempt "
                  f"{attempt}, {elapsed:.0f}s/{budget:.0f}s budget): "
                  f"{fail}", file=sys.stderr, flush=True)
            # Admission gate (VERDICT r3 item 5, hardened round 5):
            # once the attempt floor is met, a new attempt is admitted
            # only if its WHOLE worst-case cost — the retry sleep that
            # precedes it PLUS its dial-probe timeout — still fits the
            # budget.  History: the r3-era gate (elapsed >= budget)
            # admitted an attempt whenever any budget remained, so the
            # last probe could overrun by up to --probe-timeout —
            # BENCH_r03 recorded 11 attempts to 1620 s against a
            # 1500 s budget, surviving the driver watchdog only on its
            # grace margin.  The first fix reserved the probe but then
            # TRUNCATED the sleep to squeeze one more attempt in —
            # hammering the transport at the budget's edge, when
            # spacing is the point of the backoff.  Now every admitted
            # attempt is charged probe_reserve + init_backoff up
            # front: attempts keep their full spacing and the error
            # path's elapsed_s <= budget whenever the budget (not the
            # floor) ends the loop (regression test from the r03
            # timings in tests/test_bench.py).
            probe_reserve = (args.probe_timeout
                             if args.probe_timeout
                             and _expects_accelerator(args) else 0.0)
            if (attempt >= min_attempts
                    and elapsed + args.init_backoff + probe_reserve
                    > budget):
                break
            if args.init_backoff:
                time.sleep(args.init_backoff)
        # Out of retries: emit the standard JSON line WITH an error field
        # so the driver parses a result either way (round 1 recorded
        # parsed=null when this died with a bare traceback).
        elapsed = time.monotonic() - t_start
        _report_error(args, f"device backend unavailable after "
                            f"{attempt} attempts over {elapsed:.0f}s "
                            f"(budget {budget:.0f}s): {last_err}",
                      attempts=attempt, elapsed_s=round(elapsed, 1))
        return 0
    finally:
        if timer is not None:  # in-process callers outlive the bench
            timer.cancel()


def _expects_accelerator(args) -> bool:
    """Should this run land on a non-CPU backend?  ``--device tpu`` is
    explicit; ``--device`` unset means "whatever the environment is set
    up for", so expect an accelerator iff the env names one (the driver
    runs with ``JAX_PLATFORMS=axon``; a bare CPU dev box has neither).
    Used to (a) decide whether the dial probe is worth a subprocess and
    (b) reject a silent CPU fallback as a retryable failure rather than
    recording CPU throughput with no error field."""
    if args.device == "tpu":
        return True
    if args.device == "cpu":
        return False
    envp = os.environ.get("JAX_PLATFORMS", "")
    return any(p in envp for p in ("axon", "tpu", "cuda", "rocm"))


def _probe_backend(timeout: float) -> str | None:
    """Dial the device transport in a THROWAWAY subprocess bounded by
    ``timeout``.  Returns None when healthy, else a reason string.

    The axon tunnel's dominant failure mode is a wedge — PJRT client
    creation hangs for hours with no error — which an in-process call
    cannot recover from (the C++ dial is uninterruptible).  Probing
    out-of-process converts a wedge into a normal retryable attempt.
    The subprocess inherits the environment, so it dials the same
    platform the in-process run would; a probe that resolves to CPU
    (silent plugin-init fallback) is a failure — only called when an
    accelerator is expected.
    """
    import subprocess

    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; print('resolved=' + jax.default_backend())"],
            capture_output=True, text=True, timeout=timeout)
    except subprocess.TimeoutExpired:
        return f"dial probe wedged (>{timeout:.0f}s, no response)"
    if r.returncode != 0:
        tail = (r.stderr or "").strip().splitlines()
        return f"dial probe rc={r.returncode}: {tail[-1] if tail else '?'}"
    if "resolved=cpu" in r.stdout:
        return ("accelerator expected but backend resolved to cpu "
                "(plugin init fell back silently)")
    return None


def _is_unavailable(e: Exception) -> bool:
    """True for device-transport init/compile failures worth retrying."""
    msg = f"{type(e).__name__}: {e}"
    return ("UNAVAILABLE" in msg
            or "Unable to initialize backend" in msg
            or "DEADLINE_EXCEEDED" in msg)


def _reset_backends() -> None:
    """Drop cached (failed) jax backend state so the next attempt
    re-dials the transport instead of replaying the cached error."""
    try:
        import jax.extend.backend

        jax.extend.backend.clear_backends()
    except Exception:
        pass
    try:
        import jax._src.xla_bridge as xb

        xb._backend_errors.clear()
    except Exception:
        pass


def _report_error(args, reason: str, **extra) -> bool:
    if not _claim_report():
        return False  # a genuine result line already won the race
    line = {
        "metric": f"{args.mode}_throughput[{args.config}@"
                  f"{args.image_size}px,{args.device or 'auto'}]",
        "value": 0.0,
        "unit": "images/sec/chip",
        "vs_baseline": 0.0,
        "error": reason,
        **extra,
    }
    print(json.dumps(line), flush=True)
    # Error runs are part of the trajectory too (the BENCH_r01-r03
    # rounds were ALL error lines — their absence from any history is
    # exactly the gap this fixes).
    _append_history(dict(line, ts=round(time.time(), 3)))
    return True


def _run(args):
    from distributed_sod_project_tpu.utils.platform import select_platform

    select_platform(args.device)

    from distributed_sod_project_tpu.configs import apply_overrides, get_config

    hw = args.image_size

    if args.mode == "data":
        # Pure host path: never touch a jax backend (device_count would
        # dial the TPU transport for nothing).
        batch = args.batch_per_chip
        cfg = get_config(args.config)
        cfg = apply_overrides(
            cfg, [f"global_batch_size={batch}",
                  f"data.image_size={hw},{hw}"] + list(args.overrides))
        _reject_non_train_chunking(args, cfg)
        dt = _bench_data(cfg, batch, args.steps, args.warmup,
                         overrides=args.overrides)
        return _report(args, batch * args.steps / dt, "cpu", 1,
                       mode=f"data[{cfg.data.backend}]")

    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributed_sod_project_tpu.models import build_model
    from distributed_sod_project_tpu.parallel.mesh import (
        batch_sharding, make_mesh, replicated_sharding)
    from distributed_sod_project_tpu.train import (
        build_optimizer, create_train_state)

    n_chips = jax.device_count()
    if _expects_accelerator(args) and jax.default_backend() == "cpu":
        # Belt-and-braces for --probe-timeout 0: never record CPU
        # throughput with no error field when a TPU was expected.
        raise RuntimeError(
            "UNAVAILABLE: accelerator expected but jax resolved to the "
            "cpu backend (plugin init fell back silently)")
    batch = args.batch_per_chip * n_chips

    cfg = get_config(args.config)
    cfg = apply_overrides(cfg, [f"global_batch_size={batch}"]
                          + list(args.overrides))
    _reject_non_train_chunking(args, cfg)

    if args.mode == "serve":
        return _bench_serve(args, cfg)

    mesh = make_mesh(cfg.mesh)
    model = build_model(cfg.model)
    tx, sched = build_optimizer(cfg.optim, 1000)

    rng = np.random.RandomState(0)
    host_batch = {
        "image": rng.randn(batch, hw, hw, 3).astype(np.float32),
        "mask": (rng.rand(batch, hw, hw, 1) > 0.5).astype(np.float32),
    }
    if cfg.data.use_depth:
        host_batch["depth"] = rng.randn(batch, hw, hw, 1).astype(np.float32)

    state = create_train_state(jax.random.key(0), model, tx, host_batch)
    if args.mode == "eval":
        # Forward-only: ship just the eval variables, not the optimizer
        # slots (3-4x the param bytes replicated onto every chip).
        from distributed_sod_project_tpu.train.state import TrainState

        state = TrainState(step=state.step, params=state.params,
                           batch_stats=state.batch_stats, opt_state=())
    state = jax.device_put(state, replicated_sharding(mesh))
    dev_batch = jax.device_put(host_batch, batch_sharding(mesh))

    # Each mode provides run_step() -> sync token; sync is a HOST FETCH
    # (device_get), not jax.block_until_ready: through remote-device
    # transports (axon) the latter can resolve before execution drains,
    # inflating throughput ~50x (measured — docs/PERFORMANCE.md).  The
    # fetched value must depend on EVERY device's shard: the train
    # metrics are pmean-replicated; eval sums the sharded output.
    if args.mode == "eval":
        from distributed_sod_project_tpu.metrics.streaming import (
            init_fbeta_state, update_fbeta_state)
        from distributed_sod_project_tpu.train.step import make_eval_step

        estep = make_eval_step(model, mesh)
        # The measured eval step is forward + DEVICE-SIDE metric
        # accumulation (the test.py --fast-metrics / inline-eval hot
        # loop), so the number includes what eval actually does.  The
        # metric state also chains every step: eval forwards are
        # independent, so without the carry the final fetch would only
        # prove the last dispatch drained.  ONE jit for forward+update:
        # two dispatches per step pay the remote-transport round-trip
        # twice (per-dispatch latency dominates small batches there).
        @partial(jax.jit, donate_argnums=0)
        def eval_and_update(acc_state, s, b):
            return update_fbeta_state(acc_state, estep(s, b), b["mask"])

        acc = [init_fbeta_state()]

        def run_step():
            # Exactly ONE dispatch per step; the chained (donated) acc
            # state is the sync token.  The reductions that prove every
            # shard landed happen once, in sync(), after the loop.
            acc[0] = eval_and_update(acc[0], state, dev_batch)
            return acc[0]

        def sync(a):
            return float(a.mae_sum + a.f_curve_sum.sum())
    else:
        # From the RESOLVED config, not the flag: --set
        # steps_per_dispatch=k (or a config default) must count images
        # and skip the cost model exactly like --steps-per-dispatch k.
        k_spd = cfg.steps_per_dispatch
        # The unified rules engine (the only engine): same preset
        # routing as fit() (DP / FSDP / GSPMD+ZeRO / SP), so --set
        # parallel.preset=fsdp / parallel.zero=1 /
        # parallel.comm_bucket_mb=N / parallel.grad_compression=int8_ef
        # sweep arms bench the REAL program.  Re-places the state
        # (ZeRO/FSDP shard buffers over `data`); the comm plan is
        # priced offline by tools/roofline.py --comm, not here.
        from distributed_sod_project_tpu.parallel.engine import (
            prepare_train_step)

        state, step, _plan = prepare_train_step(
            cfg, model, tx, mesh, sched, state,
            steps_per_dispatch=k_spd)
        if k_spd > 1:
            # One resident k-stacked batch; each timed "step" below is
            # one dispatch = k train steps (the A/B isolates dispatch
            # overhead: device work per image is identical).  The spec
            # comes from the builders' single source of truth so the
            # bench can never place chunks differently than fit does.
            from jax.sharding import NamedSharding

            from distributed_sod_project_tpu.parallel.mesh import (
                batch_spec)
            from distributed_sod_project_tpu.train.step import (
                chunk_batch_spec)

            chunk_host = {key: np.stack([v] * k_spd)
                          for key, v in host_batch.items()}
            dev_batch = jax.device_put(
                chunk_host,
                NamedSharding(mesh, chunk_batch_spec(batch_spec())))
        carry = [state]

        def run_step():
            carry[0], metrics = step(carry[0], dev_batch)
            return metrics["total"]

        def sync(total):
            # Chunked: (k,) per-step losses — reduce so the fetch
            # depends on every step; scalar at k=1 as before.
            return float(np.asarray(jax.device_get(total)).sum())

    for _ in range(args.warmup):  # compile + stabilise
        token = run_step()
    if args.warmup:  # --warmup 0 is honored: compile lands in the timed
        sync(token)  # window, which is what a cold-start bench wants

    if args.profile_dir:
        jax.profiler.start_trace(args.profile_dir)
    try:
        t0 = time.perf_counter()
        for _ in range(args.steps):
            token = run_step()
        sync(token)
        dt = time.perf_counter() - t0
    finally:
        if args.profile_dir:  # a retried attempt must not find the
            jax.profiler.stop_trace()  # profiler still active

    if args.mode == "eval":
        # ADVICE r3: lower with the ACTUAL final acc object — a fresh
        # host-side init_fbeta_state() has different sharding/commit-
        # ment, which can miss the executable cache and trigger a
        # (post-timing, but slow on device backends) second compile.
        extra = _cost_fields(eval_and_update, dt / args.steps,
                             acc[0], state, dev_batch)
        k_spd = 1
    elif k_spd > 1:
        # XLA's cost model is ambiguous about while-loop trip counts —
        # a mislabeled per-step GFLOPs/MFU is worse than none.
        extra = {"steps_per_dispatch": k_spd}
    else:
        extra = _cost_fields(step, dt / args.steps, state, dev_batch)
    return _report(args, batch * args.steps * k_spd / dt,
                   jax.devices()[0].platform, n_chips, **extra)


def _cost_fields(jitted, dt_step: float, *call_args) -> dict:
    """FLOPs/step from XLA's cost model → ``gflops_per_step_chip``
    (cost_analysis is per-device under jit-of-shard_map, so the value
    is already the per-chip share) and, where the peak is known,
    ``mfu``.

    ``lower().compile()`` hits the in-process executable cache (the
    step just ran), so this is bookkeeping, not a second compile.  MFU
    uses the per-chip dense peak for the device generation; unknown
    kinds report FLOPs only.  Best-effort: any failure returns {} —
    the throughput number must never die on the cost model.
    """
    import jax

    try:
        cost = jitted.lower(*call_args).compile().cost_analysis()
        if isinstance(cost, (list, tuple)):  # old jax: one dict per device
            cost = cost[0]
        flops = float(cost.get("flops", 0.0))
    except Exception:  # noqa: BLE001 — optional diagnostics only
        return {}
    if flops <= 0 or dt_step <= 0:
        return {}
    # cost_analysis is per-program; under jit-of-shard_map that is the
    # per-device share.  Dense bf16/fp32-accum peak per chip:
    peaks = {"v5 lite": 197e12, "v5e": 197e12, "v5p": 459e12,
             "v4": 275e12, "v6": 918e12, "trillium": 918e12}
    kind = ""
    try:
        kind = jax.devices()[0].device_kind.lower()
    except Exception:  # noqa: BLE001
        pass
    out = {"gflops_per_step_chip": round(flops / 1e9, 1)}
    for tag, peak in peaks.items():
        if tag in kind:
            out["mfu"] = round(flops / dt_step / peak, 4)
            break
    return out


def _reject_non_train_chunking(args, cfg) -> None:
    """Mirror of the --steps-per-dispatch flag guard for the --set
    spelling: a non-train mode never builds the chunked program, so a
    steps_per_dispatch override there would record an "A/B leg" under
    a distinct baseline key that measured the ordinary program —
    exactly the key contamination the tagging exists to prevent."""
    if args.mode != "train" and cfg.steps_per_dispatch > 1:
        raise SystemExit(
            f"--set steps_per_dispatch={cfg.steps_per_dispatch} only "
            f"applies to --mode train (mode {args.mode!r} runs the "
            "ordinary program; the override would tag a baseline key "
            "without changing what was measured)")


def _bench_serve(args, cfg) -> int:
    """--mode serve: stand up the real HTTP serving stack in-process
    (random-init weights — the bench measures the serving machinery,
    not a particular checkpoint) and drive it with the closed-loop
    generator.  The headline value is served imgs/sec; p50/p95/p99 ride
    along so --baseline-file regression-tracks the latency tail too.

    Single-device on purpose: the engine dispatches to the default
    device, so per-chip == total and the baseline key's platform tag
    still distinguishes cpu/tpu runs.
    """
    import threading

    import jax

    from distributed_sod_project_tpu.configs import apply_overrides
    from distributed_sod_project_tpu.serve.engine import InferenceEngine
    from distributed_sod_project_tpu.serve.loadgen import run_loadgen
    from distributed_sod_project_tpu.serve.server import make_server

    hw = args.image_size
    cfg = apply_overrides(cfg, [f"data.image_size={hw},{hw}"])
    engine = InferenceEngine.from_random_init(cfg).start()
    srv = make_server(engine, "127.0.0.1", 0)
    thread = threading.Thread(target=srv.serve_forever, daemon=True)
    thread.start()
    url = f"http://127.0.0.1:{srv.server_address[1]}"
    concurrency = max(cfg.serve.batch_buckets)
    try:
        if args.warmup:  # engine.start() AOT-warmed the programs; this
            run_loadgen(url, mode="closed", concurrency=1,  # warms HTTP
                        requests=args.warmup, sizes=((hw, hw),), seed=0)
        res = run_loadgen(url, mode="closed", concurrency=concurrency,
                          requests=args.steps, sizes=((hw, hw),), seed=1)
    finally:
        srv.shutdown()
        srv.server_close()
        engine.stop()
    if not res["ok"]:
        _report_error(args, f"serve bench completed 0/{args.steps} "
                            "requests")
        return 1
    extra = {k: res[k] for k in ("p50_ms", "p95_ms", "p99_ms")}
    extra.update(shed=res["shed"], expired=res["expired"],
                 concurrency=concurrency,
                 precision=cfg.serve.precision)
    return _report(args, res["ok"] / res["elapsed_s"],
                   jax.devices()[0].platform, 1, mode="serve", **extra)


def _bench_data(cfg, batch: int, steps: int, warmup: int,
                overrides=()) -> float:
    """Time the host input pipeline alone: seconds to produce ``steps``
    batches (epochs cycled as needed) on the configured backend.

    Use enough --steps to overwhelm the backend's prefetch depth:
    deep-prefetch backends (grain) serve short runs from buffers filled
    during warmup — measured in-sandbox: grain "203 img/s" over 10
    steps collapsed to its true ~5 img/s sustained rate at 40 steps,
    while the host backend reported the same number at both lengths.
    """
    import itertools

    from distributed_sod_project_tpu.data import resolve_dataset
    from distributed_sod_project_tpu.data.tfdata import make_loader

    dataset = resolve_dataset(cfg.data)
    # The bench consumes each batch immediately, so UNLESS the user
    # said otherwise it runs the zero-copy posture the train loop uses
    # on hardware: recycled ring buffers.  An explicit --set
    # data.ring_buffers=<n> (including 0 = off, the A/B leg for the
    # allocating path) always wins.
    ring = cfg.data.ring_buffers
    user_set_ring = any(o.split("=", 1)[0].strip() == "data.ring_buffers"
                        for o in overrides)
    if not user_set_ring and ring == 0:
        ring = cfg.data.lookahead + 3
    loader = make_loader(
        dataset, cfg.data, global_batch_size=batch, shard_id=0,
        num_shards=1, shuffle=True, seed=cfg.seed, hflip=cfg.data.hflip,
        rotate_degrees=cfg.data.rotate_degrees,
        color_jitter=cfg.data.color_jitter,
        num_workers=cfg.data.num_workers,
        ring_buffers=ring)

    if loader.steps_per_epoch <= 0:
        raise SystemExit(
            f"global batch {batch} > dataset size {len(dataset)}: the "
            "loader yields zero batches per epoch (drop_last) — shrink "
            "--batch-per-chip or grow data.synthetic_size")

    def batches():
        for epoch in itertools.count():
            loader.set_epoch(epoch)
            yield from iter(loader)

    it = batches()
    for _ in range(warmup):
        next(it)
    t0 = time.perf_counter()
    for _ in range(steps):
        next(it)
    return time.perf_counter() - t0


def _report(args, imgs_per_sec: float, platform: str, n_chips: int,
            mode: str | None = None, **extra) -> int:
    """One JSON line + self-relative baseline tracking (the first run
    per (config, size, platform, mode) seeds ``bench_baseline.json``).
    Returns the process exit code: 0, or 3 when --baseline-file +
    --fail-below flags a regression."""
    # Claimed BEFORE the print: the watchdog must never append an error
    # line after (or while) a genuine result is being written — losing a
    # real number is worse than the timer dying with the result unsent.
    _claim_report()
    mode = mode or args.mode
    per_chip = imgs_per_sec / n_chips
    from distributed_sod_project_tpu.utils import envvars

    base_path = (envvars.read("DSOD_BENCH_BASELINE")
                 or os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                 "bench_baseline.json"))
    # Batch, --set overrides, AND program-affecting env vars are in the
    # key: throughput scales with batch (dispatch-latency amortisation)
    # and the others change the compiled program (remat, kernels,
    # resize impl, flash blocks), so baselines only compare like with
    # like.  (Round-2 lesson: a remat-on run seeded b64's key and every
    # remat-off run then reported a bogus vs_baseline; the same class
    # of contamination applied to DSOD_RESIZE_IMPL=xla A/B legs.)
    key = (f"{args.config}-{args.image_size}-b{args.batch_per_chip}"
           f"-{platform}")
    if args.overrides:
        key += "-" + ",".join(sorted(args.overrides))
    env_tags = []
    for k in _PROGRAM_ENV_VARS:
        v = envvars.read(k)
        if not v:
            continue
        if k == "DSOD_STEM_IMPL" and v == "s2d" and args.image_size % 2:
            # ADVICE r3: odd H/W forces the plain-stem fallback
            # (models/backbones/resnet.py) — tag the key with what
            # actually ran so an s2d A/B leg at an odd size never
            # records mislabeled numbers.
            v = "s2d[plain-stem-fallback]"
        env_tags.append(f"{k}={v}")
    if env_tags:
        key += "-env:" + ",".join(sorted(env_tags))
    if mode != "train":
        key += f"-{mode}"
    base = {}
    if os.path.exists(base_path):
        with open(base_path) as f:
            base = json.load(f)
    if key not in base:
        base[key] = per_chip
        with open(base_path, "w") as f:
            json.dump(base, f, indent=2)
    vs = per_chip / base[key] if base[key] else 1.0

    rc = 0
    if args.baseline_file:
        # Regression mode against a CHECKED-IN baseline: seed on first
        # contact, compare forever after (tools/bench_data.sh).
        recorded = {}
        if os.path.exists(args.baseline_file):
            with open(args.baseline_file) as f:
                recorded = json.load(f)
        if key in recorded and recorded[key]:
            extra["vs_recorded"] = round(per_chip / recorded[key], 3)
            if args.fail_below and extra["vs_recorded"] < args.fail_below:
                rc = 3
        else:
            recorded[key] = round(per_chip, 2)
            with open(args.baseline_file, "w") as f:
                json.dump(recorded, f, indent=2, sort_keys=True)
                f.write("\n")
            extra["recorded"] = True

    line = {
        "metric": f"{mode}_throughput[{args.config}@"
                  f"{args.image_size}px,{platform}x{n_chips}]",
        "value": round(per_chip, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(vs, 3),
        **extra,
    }
    print(json.dumps(line), flush=True)
    _append_history(dict(line, ts=round(time.time(), 3), key=key))
    return rc


def _append_history(entry: dict) -> None:
    """Accumulate every run's one-line summary in
    ``tools/bench_history.jsonl`` (override: DSOD_BENCH_HISTORY; empty
    string disables) so the perf trajectory exists ACROSS rounds —
    bench_baseline.json keeps only one number per key, which is why
    the BENCH trajectory was empty before this file.  Append-only
    JSONL, never raises: history must not cost a result."""
    from distributed_sod_project_tpu.utils import envvars

    path = envvars.read("DSOD_BENCH_HISTORY")
    if path == "":
        return
    if path is None:
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "tools", "bench_history.jsonl")
    try:
        with open(path, "a") as f:
            f.write(json.dumps(entry) + "\n")
    except OSError:
        pass


if __name__ == "__main__":
    raise SystemExit(main())
