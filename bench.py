#!/usr/bin/env python
"""Throughput benchmark — prints ONE JSON line for the driver.

Measures the governing metric (BASELINE.json:2): images/sec/chip for the
flagship data-parallel train step (MINet-ResNet50, 320×320, bf16), the
TPU analogue of the reference's 8×V100 DDP throughput posture.

``vs_baseline`` is self-relative: the reference's V100 number was
unobtainable (BASELINE.md), so the first recorded run seeds
``bench_baseline.json`` and later runs report the ratio against it.
"""

from __future__ import annotations

import argparse
import json
import os
import time


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--config", default="minet_r50_dp")
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--warmup", type=int, default=3)
    p.add_argument("--batch-per-chip", type=int, default=8)
    p.add_argument("--image-size", type=int, default=320)
    p.add_argument("--device", default=None, choices=["tpu", "cpu", None])
    p.add_argument("--mode", default="train", choices=["train", "eval"],
                   help="train: full DP step (default); eval: forward-only "
                        "sigmoid inference, the test.py hot loop")
    p.add_argument("--set", dest="overrides", action="append", default=[],
                   metavar="PATH=VALUE",
                   help="dotted config override, e.g. --set "
                        "loss.fused_kernel=true --set model.remat=true "
                        "(bench always times the shard_map DP step)")
    p.add_argument("--profile-dir", default=None,
                   help="capture a jax.profiler trace of the timed window")
    args = p.parse_args(argv)

    from distributed_sod_project_tpu.utils.platform import select_platform

    select_platform(args.device)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributed_sod_project_tpu.configs import apply_overrides, get_config
    from distributed_sod_project_tpu.models import build_model
    from distributed_sod_project_tpu.parallel.mesh import (
        batch_sharding, make_mesh, replicated_sharding)
    from distributed_sod_project_tpu.train import (
        build_optimizer, create_train_state, make_train_step)

    n_chips = jax.device_count()
    batch = args.batch_per_chip * n_chips
    hw = args.image_size

    cfg = get_config(args.config)
    cfg = apply_overrides(cfg, [f"global_batch_size={batch}"]
                          + list(args.overrides))

    mesh = make_mesh(cfg.mesh)
    model = build_model(cfg.model)
    tx, sched = build_optimizer(cfg.optim, 1000)

    rng = np.random.RandomState(0)
    host_batch = {
        "image": rng.randn(batch, hw, hw, 3).astype(np.float32),
        "mask": (rng.rand(batch, hw, hw, 1) > 0.5).astype(np.float32),
    }
    if cfg.data.use_depth:
        host_batch["depth"] = rng.randn(batch, hw, hw, 1).astype(np.float32)

    state = create_train_state(jax.random.key(0), model, tx, host_batch)
    state = jax.device_put(state, replicated_sharding(mesh))
    dev_batch = jax.device_put(host_batch, batch_sharding(mesh))

    # Each mode provides run_step() -> sync token; sync is a HOST FETCH
    # (device_get), not jax.block_until_ready: through remote-device
    # transports (axon) the latter can resolve before execution drains,
    # inflating throughput ~50x (measured — docs/PERFORMANCE.md).  The
    # fetched value must depend on EVERY device's shard: the train
    # metrics are pmean-replicated; eval sums the sharded output.
    if args.mode == "eval":
        from distributed_sod_project_tpu.train.step import make_eval_step

        estep = make_eval_step(model, mesh)
        # Eval steps are independent (no state carry), so the sync token
        # must chain THROUGH every step or the final fetch only proves
        # the last dispatch drained: fold each output into an
        # accumulator and fetch that.
        acc = [jnp.zeros((), jnp.float32)]

        def run_step():
            acc[0] = acc[0] + jnp.sum(estep(state, dev_batch))
            return acc[0]

        def sync(token):
            return float(token)
    else:
        step = make_train_step(model, cfg.loss, tx, mesh, schedule=sched,
                               remat=cfg.model.remat)
        carry = [state]

        def run_step():
            carry[0], metrics = step(carry[0], dev_batch)
            return metrics["total"]

        def sync(total):
            return float(total)

    for _ in range(max(args.warmup, 1)):  # compile + stabilise (≥1: the
        token = run_step()                # sync token must exist)
    sync(token)

    if args.profile_dir:
        jax.profiler.start_trace(args.profile_dir)
    t0 = time.perf_counter()
    for _ in range(args.steps):
        token = run_step()
    sync(token)
    dt = time.perf_counter() - t0
    if args.profile_dir:
        jax.profiler.stop_trace()

    imgs_per_sec = batch * args.steps / dt
    per_chip = imgs_per_sec / n_chips

    base_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "bench_baseline.json")
    key = f"{args.config}-{hw}-{jax.devices()[0].platform}"
    if args.mode != "train":
        key += f"-{args.mode}"
    base = {}
    if os.path.exists(base_path):
        with open(base_path) as f:
            base = json.load(f)
    if key not in base:
        base[key] = per_chip
        with open(base_path, "w") as f:
            json.dump(base, f, indent=2)
    vs = per_chip / base[key] if base[key] else 1.0

    print(json.dumps({
        "metric": f"{args.mode}_throughput[{args.config}@{hw}px,"
                  f"{jax.devices()[0].platform}x{n_chips}]",
        "value": round(per_chip, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(vs, 3),
    }))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
